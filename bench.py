"""Headline benchmark: scheduler ticks at 1M pending tasks x 10k nodes.

North star (BASELINE.md / BASELINE.json): snapshot the pending-task queue
(deduped into scheduling classes, task_spec.h:297) and per-node resource
vectors, solve the batched task->node assignment on TPU in <50 ms/tick on
a single host.  The reference's greedy loop
(``HybridSchedulingPolicy::Schedule`` per task over per-node hash maps)
is replaced by ``ray_tpu.scheduler.jax_backend``'s dense [C,R]x[N,R]
bucketized waterfill.

TPU-resident design measured here (how a raylet colocated with the chip
would run):
  * world state (avail/total [N,R], class demand shapes [C,R]), the
    per-class pending queue AND the inflight-work matrix live on device —
    world uploaded once by ``prepare_device``, queue + availability +
    inflight carried as scan state;
  * the loop is CLOSED on device in STATE, not just queue: tick k's
    placements subtract capacity that stays subtracted, a geometric
    completion process (per-class rate rho) releases it back, and the
    unplaced remainder carries into tick k+1 — only the exogenous
    arrival stream is staged ahead (a real raylet streams it in), never
    future queue or availability snapshots;
  * each tick ships a fixed-size sparse assignment (idx,val pairs) +
    validation bits back; ticks stream through one device program
    (``solve_stream``) so dispatch latency amortizes.
The same kernel family also runs the live dispatch path: a raylet's
ClusterTaskManager holds the world device-resident via
``jax_backend.DeviceRuntimeSolver`` (scheduler_backend=jax, the default),
shipping dirty-row deltas per tick — bench_runtime.py measures that
end-to-end path through ``ray_tpu.remote``.

Prints ONE JSON line:
  {"metric": ..., "value": <ms per tick>, "unit": "ms", "vs_baseline": x}
vs_baseline > 1.0 means faster than the 50 ms target.

Problem shape (config 5 of BASELINE.json, Google-cluster-trace shaped):
1,000,000 tasks in 256 scheduling classes, 10,000 heterogeneous nodes,
8 resource columns.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def build_problem(rng, num_tasks=1_000_000, C=256, N=10_000, R=8):
    # Heterogeneous fleet: small CPU nodes, big CPU nodes, TPU hosts.
    total = np.zeros((N, R), dtype=np.float32)
    kinds = rng.choice(3, size=N, p=[0.6, 0.3, 0.1])
    total[:, 0] = np.where(kinds == 0, 4, np.where(kinds == 1, 64, 8))  # CPU
    total[:, 1] = np.where(kinds == 0, 16, np.where(kinds == 1, 256, 64))  # mem GB
    total[:, 2] = np.where(kinds == 2, 4, 0)   # TPU chips
    total[:, 3] = rng.integers(0, 2, N)        # GPU-ish custom accel
    for r in range(4, R):
        total[:, r] = rng.integers(0, 8, N)    # custom resources
    used = rng.uniform(0.0, 0.6, size=(N, R)).astype(np.float32)
    avail = np.floor(total * (1.0 - used))

    # Trace-shaped demand: most classes small CPU tasks, a tail of
    # memory-heavy and accelerator classes; counts follow a power law.
    demand = np.zeros((C, R), dtype=np.float32)
    demand[:, 0] = rng.choice([0.5, 1, 2, 4], size=C, p=[0.4, 0.4, 0.15, 0.05])
    demand[:, 1] = rng.choice([1, 2, 4, 16], size=C, p=[0.5, 0.3, 0.15, 0.05])
    accel_classes = rng.random(C) < 0.08
    demand[accel_classes, 2] = rng.choice([1, 4], size=accel_classes.sum())
    raw = rng.pareto(1.5, size=C) + 1.0
    counts = np.floor(raw / raw.sum() * num_tasks).astype(np.int64)
    counts[-1] += num_tasks - counts.sum()
    accel_node = total[:, 2] > 0
    return avail, total, demand, counts, accel_node, accel_classes


def arrival_stream(rng, counts, ticks, per_tick=130_000):
    """Exogenous per-tick task arrivals: tick 0 delivers the full 1M
    backlog; later ticks deliver ~placement-rate volume (so the pending
    queue hovers around 1M) with a rotating per-class mix."""
    C = counts.shape[0]
    stream = np.empty((ticks, C), dtype=np.int64)
    stream[0] = counts
    frac = counts / counts.sum()
    for k in range(1, ticks):
        mix = np.roll(frac, k)
        row = np.floor(mix * per_tick).astype(np.int64)
        row += rng.integers(0, 3, size=C)
        stream[k] = row
    return stream


def _probe():
    """Bounded-timeout subprocess probe of the configured backend
    (ray_tpu._private.tpu_probe) — a sick chip can never hang this
    process (BENCH_r05 was rc=1 and MULTICHIP_r05 rc=124 from exactly
    that).  Prints a structured marker when the chip is unusable."""
    from ray_tpu._private.tpu_probe import (chip_unavailable_marker,
                                            probe_backend)
    probe = probe_backend(timeout=90.0, retries=2)
    if not probe.get("ok"):
        print(chip_unavailable_marker(probe, stage="bench",
                                      fallback="cpu"), flush=True)
    return probe


def _init_backend(probe):
    """Bring up the probed backend in-process, falling back to CPU.
    Returns the backend name, or None when no backend at all comes up —
    the bench must emit parseable JSON and rc=0 in that case, not a
    backend-init traceback."""
    if probe.get("ok"):
        try:
            import jax
            jax.devices()      # probe proved this returns promptly
            return jax.default_backend()
        except Exception:
            pass
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax.default_backend()
    except Exception:
        return None


def _model_bench_row(on_cpu: bool):
    """Run bench_model.py (transformer train-step MFU) in a subprocess
    and return its parsed JSON row, or a structured skip dict.  The
    driver only ever invokes bench.py, so the MFU number must ride this
    process's output (VERDICT weak-#2: MFU had never been measured)."""
    env = dict(os.environ)
    if on_cpu:
        # The parent already decided the TPU is unusable: the child
        # must not retry (and hang on) the real backend.
        env["JAX_PLATFORMS"] = "cpu"
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_model.py")
    try:
        proc = subprocess.run([sys.executable, path], env=env,
                              capture_output=True, text=True,
                              timeout=1200)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "bench_model timed out"}
    if proc.returncode != 0 or not proc.stdout.strip():
        return {"skipped": True,
                "reason": f"bench_model rc={proc.returncode}: "
                          f"{(proc.stderr or '')[-400:]}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except ValueError:
        return {"skipped": True, "reason": "unparseable bench_model output"}


def _dispatch_latency_rows():
    """Run bench_runtime.py --dispatch-only in a subprocess (its own
    CPU-side runtime, never touches the chip) and return the parsed
    task_dispatch_latency_p99 sweep rows (n=500/2000/5000), or a
    structured skip dict — the bench trajectory records the north-star
    p99 from every bench.py invocation."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--dispatch-only"],
            env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "dispatch bench timed out"}
    if proc.returncode != 0:
        return {"skipped": True,
                "reason": f"dispatch bench rc={proc.returncode}: "
                          f"{(proc.stderr or '')[-400:]}"}
    rows = []
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "task_dispatch_latency_p99":
            rows.append(row)
    if not rows:
        return {"skipped": True, "reason": "no dispatch-latency row in output"}
    return {"rows": rows}


def _introspection_overhead_row():
    """Run bench_runtime.py --introspection-bench in a subprocess (the
    contention arming must exist before any lock is created, hence a
    fresh process) and return the armed dispatch-latency row with its
    contention summary, or a structured skip dict."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--introspection-bench"],
            env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"skipped": True,
                "reason": "introspection bench timed out"}
    if proc.returncode != 0:
        return {"skipped": True,
                "reason": f"introspection bench rc={proc.returncode}: "
                          f"{(proc.stderr or '')[-400:]}"}
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "dispatch_latency_introspection_armed":
            return row
    return {"skipped": True,
            "reason": "no introspection row in output"}


def _profile_overhead_row():
    """Run bench_runtime.py --profile-bench in a subprocess and return
    the provenance-armed dispatch-latency row (the ISSUE-15 job
    profiler's overhead bound + its end-to-end profile of the burst),
    or a structured skip dict."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--profile-bench"],
            env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "profile bench timed out"}
    if proc.returncode != 0:
        return {"skipped": True,
                "reason": f"profile bench rc={proc.returncode}: "
                          f"{(proc.stderr or '')[-400:]}"}
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "dispatch_latency_provenance_armed":
            return row
    return {"skipped": True, "reason": "no profile row in output"}


def _broadcast_relay_row():
    """Run bench_runtime.py --broadcast-only in a subprocess (CPU-side
    runtime, never touches the chip) and return the parsed
    broadcast_relay sweep row, or a structured skip dict — the data
    plane's collective-transfer claim (relay-arm >= 3x naive, origin
    <= 2x fair share) rides every bench.py invocation."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--broadcast-only"],
            env=env, capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "broadcast bench timed out"}
    # Parse the row even on rc!=0: the sweep prints its data BEFORE
    # exiting 1 on a fair-share violation — the honest failure must
    # reach the JSON, not collapse into a skip.
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "broadcast_relay":
            if proc.returncode != 0:
                row["failed"] = True
                row["failed_rc"] = proc.returncode
            return row
    return {"skipped": True,
            "reason": f"no broadcast_relay row in output "
                      f"(rc={proc.returncode}): "
                      f"{(proc.stderr or '')[-400:]}"}


def _envelope_row():
    """Run bench_runtime.py --envelope-smoke in a subprocess (the
    envelope driver stands up its own fleet of node-host OS processes;
    this process's backend/cluster state must not leak into it) and
    return the parsed envelope_smoke row, or a structured skip dict.
    The full 50-host soak is recorded separately (ENVELOPE_r06.json);
    this row keeps the stand-up + zero-silent-loss contract riding
    every bench.py invocation at smoke cost."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--envelope-smoke"],
            env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "envelope smoke timed out"}
    # Parse the row even on rc!=0: silent loss prints its data before
    # exiting 1 — the honest failure must reach the JSON.
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "envelope_smoke":
            if proc.returncode != 0:
                row["failed"] = True
                row["failed_rc"] = proc.returncode
            return row
    return {"skipped": True,
            "reason": f"no envelope_smoke row in output "
                      f"(rc={proc.returncode}): "
                      f"{(proc.stderr or '')[-400:]}"}


def _serve_bench_row():
    """Run bench_runtime.py --serve-bench in a subprocess (the serving
    plane on CPU: closed-loop client sweep against an autoscaled,
    adaptively-batched deployment, plus the relay-vs-naive cold-start
    arm pair) and return the parsed serve_closed_loop row, or a
    structured skip dict.  --quick keeps the riding cost down; a full
    sweep is recorded per-round (BENCH_r09.json onward)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runtime.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, path, "--serve-bench", "--quick"],
            env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"skipped": True, "reason": "serve bench timed out"}
    # Parse the row even on rc!=0: a lost request or a non-chaining
    # relay arm prints its data before exiting 1 — the honest failure
    # must reach the JSON, not collapse into a skip.
    for line in proc.stdout.strip().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("metric") == "serve_closed_loop":
            if proc.returncode != 0:
                row["failed"] = True
                row["failed_rc"] = proc.returncode
            return row
    return {"skipped": True,
            "reason": f"no serve_closed_loop row in output "
                      f"(rc={proc.returncode}): "
                      f"{(proc.stderr or '')[-400:]}"}


def main():
    probe = _probe()
    probed_cpu = not probe.get("ok") or probe.get("backend") != "tpu"
    # MFU child runs BEFORE this process initializes any backend: the
    # TPU is per-process exclusive, so a parent already holding the
    # chip would starve (or wedge) the very measurement this exists
    # for.  The child gets the chip to itself, then releases it.
    model = _model_bench_row(probed_cpu)

    backend = _init_backend(probe)
    if backend is None:
        print(json.dumps({
            "metric": "scheduler_tick_1M_tasks_x_10k_nodes",
            "value": None, "unit": "ms", "skipped": True,
            "reason": "no jax backend initialized (TPU plugin failed "
                      "and no CPU fallback)",
            "mfu": None,
            "mfu_skip_reason": "no jax backend initialized",
            "dispatch_p99_ms": None,
            "dispatch_skip_reason": "no jax backend initialized",
        }))
        return 0

    rng = np.random.default_rng(42)
    # The 1M x 10k problem is sized for a TPU; on CPU run a scaled
    # replica of the same closed-loop shape so the trajectory records a
    # real number instead of a timeout/null.
    on_cpu = backend == "cpu"
    if on_cpu:
        avail, total, demand, counts, accel_node, accel_class = \
            build_problem(rng, num_tasks=50_000, C=64, N=512, R=8)
    else:
        avail, total, demand, counts, accel_node, accel_class = \
            build_problem(rng)

    from ray_tpu.scheduler.jax_backend import BatchSolver
    solver = BatchSolver(mode="waterfill")

    # One-time world-state upload (the raylet keeps this device-resident,
    # updating deltas as nodes join/leave).
    solver.prepare_device(avail, total, demand, accel_node=accel_node,
                          accel_class=accel_class, spread_threshold=0.5)

    ticks = 8 if on_cpu else 40
    stream = arrival_stream(rng, counts, ticks,
                            per_tick=(8_000 if on_cpu else 130_000))
    # Per-class geometric completion rates (mean service 2-8 ticks) —
    # the closed loop evolves availability: placements occupy capacity
    # until their completions release it.
    rho = rng.integers(2, 9, size=demand.shape[0]) / 16.0

    # Warmup (compile) + correctness: decode tick 0's sparse assignment
    # (queue = the full 1M backlog) and check capacity/count bounds on
    # the host.
    out = solver.solve_stream(stream, rho=rho)
    assert out["ok"].all(), "on-device validation failed"
    alloc0 = solver.expand_sparse(out["idx"][0], out["vals"][0])
    usage = alloc0.T.astype(np.float64) @ demand.astype(np.float64)
    assert (usage <= avail.astype(np.float64) + 1e-2).all(), \
        "capacity violated"
    assert (alloc0.sum(axis=1) <= stream[0]).all()
    placed = int(out["placed"][0])

    # Timed: K closed-loop ticks per device program.  Everything a tick
    # needs crosses the boundary inside the timed region: arrivals down,
    # sparse assignment + validation bits back; queue, availability and
    # inflight state stay device-resident between ticks.
    reps = 1 if on_cpu else 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = solver.solve_stream(stream, rho=rho)
    elapsed = time.perf_counter() - t0
    assert out["ok"].all()
    ms_per_tick = elapsed / (reps * ticks) * 1000.0

    baseline_ms = 50.0  # BASELINE.json target: <50 ms/tick
    import jax

    from ray_tpu.scheduler import jax_backend as _jb
    res = {
        "metric": "scheduler_tick_1M_tasks_x_10k_nodes",
        "value": round(ms_per_tick, 3),
        "unit": "ms",
        # Was the fused Pallas (Mosaic) fill actually live for the
        # timed region?  The 17.4 ms claim was for the fused kernel;
        # a jnp-path number must never be recorded as a Pallas number.
        # (_pallas_enabled already folds in the runtime kill-switch.)
        "pallas_fill_active": bool(_jb._pallas_enabled()),
        # The 50 ms target is sized for the full 1M x 10k problem: a
        # ratio against a CPU-scaled replica would read as beating it.
        "vs_baseline": (None if on_cpu
                        else round(baseline_ms / ms_per_tick, 2)),
        "placed_tasks": placed,
        "ticks_per_program": ticks,
        "nnz_max_per_tick": int(out["nnz"].max()),
        "classes": int(demand.shape[0]),
        "nodes": int(avail.shape[0]),
        "backend": jax.default_backend(),
    }
    if on_cpu:
        # Not the headline problem: flag it so the trajectory doesn't
        # compare CPU-scaled numbers against TPU targets.
        res["scaled_down_for_cpu"] = True

    # Model-compute axis: transformer train-step MFU rode the same
    # bench.py invocation (measured above, before this process touched
    # the chip — the driver runs nothing else).  Its own JSON line is
    # printed for the record AND folded into the headline row as an
    # ``mfu`` field (structured null + reason on skip).
    if model.get("skipped"):
        res["mfu"] = None
        res["mfu_skip_reason"] = model.get("reason")
    else:
        print(json.dumps(model))
        res["mfu"] = model.get("value")
        res["mfu_backend"] = model.get("backend")
        if model.get("backend") != "tpu":
            res["mfu_scaled_down_for_cpu"] = True
    # The two newly-kernelized solves (PG bundle packing + autoscaler
    # demand solve) get their own trajectory rows, at full scale on TPU
    # and a scaled replica on CPU (marked), structured skip on failure.
    try:
        import bench_runtime
        if on_cpu:
            pg_row = bench_runtime.bench_pg_packing(100, 512)
            auto_row = bench_runtime.bench_autoscaler_solve(1_000, 128)
            pg_row["scaled_down_for_cpu"] = True
            auto_row["scaled_down_for_cpu"] = True
        else:
            pg_row = bench_runtime.bench_pg_packing(1_000, 10_000)
            auto_row = bench_runtime.bench_autoscaler_solve(10_000, 1_000)
        res["pg_bundle_packing"] = {k: v for k, v in pg_row.items()
                                    if k != "metric"}
        res["autoscaler_solve"] = {k: v for k, v in auto_row.items()
                                   if k != "metric"}
    except Exception as err:
        res["pg_bundle_packing"] = {"skipped": True, "reason": repr(err)}
        res["autoscaler_solve"] = {"skipped": True, "reason": repr(err)}

    # North-star runtime axis: p99 task-dispatch latency, decomposed by
    # stage and swept across burst sizes (n=500/2000/5000) — measured
    # end-to-end through ray_tpu.remote by a CPU-side subprocess (the
    # chip is untouched), folded into the headline row.  The headline
    # dispatch_p99_ms stays the n=500 row for cross-round continuity.
    # Data-plane collective axis: relay-vs-naive broadcast sweep
    # (64/256 MiB x 8/16/32 in-process stores, modeled link time,
    # per-source served-bytes balance), folded as broadcast_relay.
    res["broadcast_relay"] = {
        k: v for k, v in _broadcast_relay_row().items()
        if k not in ("metric", "value", "unit")}

    # Cluster-envelope axis: the chaos-soak driver at smoke scale
    # (4 node-host OS processes, seeded faults, zero-silent-loss
    # contract), folded as envelope — the summary already carries the
    # driver's own honest cpu_throttled marking for this box.
    res["envelope"] = {
        k: v for k, v in _envelope_row().items()
        if k not in ("metric", "value", "unit")}

    # Serving axis (ISSUE 20): closed-loop p50/p99 vs offered load
    # with the saturation knee, the autoscaler's decisions, adaptive
    # batch fill, and the relay-vs-naive cold-start pair — folded as
    # serve.  The knee throughput rides as serve["knee_rps"].
    serve_row = _serve_bench_row()
    res["serve"] = {
        k: v for k, v in serve_row.items()
        if k not in ("metric", "value", "unit")}
    if not serve_row.get("skipped"):
        res["serve"]["knee_rps"] = serve_row.get("value")

    dispatch = _dispatch_latency_rows()
    if dispatch.get("skipped"):
        res["dispatch_p99_ms"] = None
        res["dispatch_skip_reason"] = dispatch.get("reason")
    else:
        rows = dispatch["rows"]
        head_row = next((r for r in rows if r.get("n") == 500), rows[0])
        for row in rows:
            print(json.dumps(row))
        res["dispatch_p99_ms"] = head_row.get("value")
        res["dispatch_p50_ms"] = head_row.get("p50_ms")
        res["dispatch_stages"] = head_row.get("stages")
        res["dispatch_lease_rpcs"] = head_row.get("lease_rpcs")
        res["dispatch_sweep"] = [
            {k: row.get(k) for k in ("n", "value", "p50_ms",
                                     "lease_rpcs", "stages")}
            for row in rows]

    # Introspection-plane overhead bound (ISSUE 13): the same n=500
    # dispatch row with flight recorder + lock-contention profiling
    # armed, compared against the unarmed headline row above; the
    # armed run's contention summary (top-5 lock wait, max loop lag)
    # rides the JSON so BENCH rows carry attribution data.
    armed = _introspection_overhead_row()
    if armed.get("skipped"):
        res["introspection_overhead"] = armed
    else:
        print(json.dumps(armed))
        baseline_p99 = res.get("dispatch_p99_ms")
        ratio = (round(armed["value"] / baseline_p99, 3)
                 if baseline_p99 else None)
        res["introspection_overhead"] = {
            "armed_p99_ms": armed["value"],
            "baseline_p99_ms": baseline_p99,
            "ratio": ratio,
            # Target: within 10% (note this 1-core runner's p99
            # varies run-to-run on identical code — see BENCH_r07 —
            # so the honest record is both numbers, not just a bit).
            "within_10pct": (ratio is not None and ratio <= 1.10),
        }
        res["contention_summary"] = armed.get("introspection")

    # Causal-profiler overhead bound (ISSUE 15): provenance capture
    # armed vs off on the same dispatch burst, plus the armed arm's
    # critical-path profile of its own burst (the end-to-end proof).
    prov = _profile_overhead_row()
    if prov.get("skipped"):
        res["provenance_overhead"] = prov
    else:
        print(json.dumps(prov))
        res["provenance_overhead"] = {
            "armed_p99_ms": prov["value"],
            "off_p99_ms": prov.get("off_p99_ms"),
            "ratio": prov.get("ratio"),
            "within_10pct": prov.get("within_10pct"),
        }
        res["job_profile_summary"] = prov.get("profile")
    print(json.dumps(res))


if __name__ == "__main__":
    sys.exit(main())
