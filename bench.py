"""Headline benchmark: one scheduler tick at 1M pending tasks x 10k nodes.

North star (BASELINE.md / BASELINE.json): snapshot the pending-task queue
(deduped into scheduling classes, task_spec.h:297) and per-node resource
vectors, solve the batched task->node assignment on TPU in <50 ms/tick on a
single host.  The reference's greedy loop
(``HybridSchedulingPolicy::Schedule`` per task over per-node hash maps)
is replaced by ``ray_tpu.scheduler.jax_backend``'s dense [C,R]x[N,R] solve.

Prints ONE JSON line:
  {"metric": ..., "value": <ms per tick>, "unit": "ms", "vs_baseline": x}
vs_baseline > 1.0 means faster than the 50 ms target.

Problem shape (config 5 of BASELINE.json, Google-cluster-trace shaped):
1,000,000 tasks in 256 scheduling classes, 10,000 heterogeneous nodes,
8 resource columns.
"""

import json
import sys
import time

import numpy as np


def build_problem(rng, num_tasks=1_000_000, C=256, N=10_000, R=8):
    # Heterogeneous fleet: small CPU nodes, big CPU nodes, TPU hosts.
    total = np.zeros((N, R), dtype=np.float32)
    kinds = rng.choice(3, size=N, p=[0.6, 0.3, 0.1])
    total[:, 0] = np.where(kinds == 0, 4, np.where(kinds == 1, 64, 8))  # CPU
    total[:, 1] = np.where(kinds == 0, 16, np.where(kinds == 1, 256, 64))  # mem GB
    total[:, 2] = np.where(kinds == 2, 4, 0)   # TPU chips
    total[:, 3] = rng.integers(0, 2, N)        # GPU-ish custom accel
    for r in range(4, R):
        total[:, r] = rng.integers(0, 8, N)    # custom resources
    used = rng.uniform(0.0, 0.6, size=(N, R)).astype(np.float32)
    avail = np.floor(total * (1.0 - used))

    # Trace-shaped demand: most classes small CPU tasks, a tail of
    # memory-heavy and accelerator classes; counts follow a power law.
    demand = np.zeros((C, R), dtype=np.float32)
    demand[:, 0] = rng.choice([0.5, 1, 2, 4], size=C, p=[0.4, 0.4, 0.15, 0.05])
    demand[:, 1] = rng.choice([1, 2, 4, 16], size=C, p=[0.5, 0.3, 0.15, 0.05])
    accel_classes = rng.random(C) < 0.08
    demand[accel_classes, 2] = rng.choice([1, 4], size=accel_classes.sum())
    raw = rng.pareto(1.5, size=C) + 1.0
    counts = np.floor(raw / raw.sum() * num_tasks).astype(np.int64)
    counts[-1] += num_tasks - counts.sum()
    accel_node = total[:, 2] > 0
    return avail, total, demand, counts, accel_node, accel_classes


def main():
    rng = np.random.default_rng(42)
    avail, total, demand, counts, accel_node, accel_class = build_problem(rng)

    from ray_tpu.scheduler.jax_backend import BatchSolver
    solver = BatchSolver(mode="waterfill")

    # Warmup (compile) + correctness check on the real solve.
    alloc = solver.solve_matrices(avail, total, demand, counts,
                                  accel_node, accel_class, 0.5)
    usage = alloc.T.astype(np.float64) @ demand.astype(np.float64)
    assert (usage <= avail.astype(np.float64) + 1e-2).all(), \
        "capacity violated"
    assert (alloc.sum(axis=1) <= counts).all()
    placed = int(alloc.sum())

    # Timed ticks: fresh availability each tick (host->device transfer
    # included — that IS the tick cost the raylet would pay).
    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        solver.solve_matrices(avail, total, demand, counts,
                              accel_node, accel_class, 0.5)
    elapsed = time.perf_counter() - t0
    ms_per_tick = elapsed / iters * 1000.0

    baseline_ms = 50.0  # BASELINE.json target: <50 ms/tick
    import jax
    out = {
        "metric": "scheduler_tick_1M_tasks_x_10k_nodes",
        "value": round(ms_per_tick, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / ms_per_tick, 2),
        "placed_tasks": placed,
        "classes": int(demand.shape[0]),
        "nodes": int(avail.shape[0]),
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
