#!/usr/bin/env python
"""Cluster-scale envelope / chaos soak driver (ROADMAP open item 1).

Thin runnable wrapper over :mod:`ray_tpu._private.envelope` — the same
driver backs ``ray-tpu envelope`` and ``bench_runtime.py
--envelope-smoke``.  Typical runs:

    # The recorded 50-host soak (writes ENVELOPE_r06.json):
    python tools/envelope.py --hosts 50 --actors 10000 --pgs 1000

    # Quick smoke (4 hosts, small everything, one fault):
    python tools/envelope.py --hosts 4 --actors 40 --pgs 8 \
        --broadcast 8:2 --chaos-events 2 --out /tmp/envelope.json
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ray_tpu._private.envelope import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
