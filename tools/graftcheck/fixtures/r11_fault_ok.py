"""R11 negative contrast: every armed/asserted point name matches a
real hook() site."""

from ray_tpu._private import fault_injection


def spill(data):
    fault_injection.hook("store.spill")
    return bytes(data)


def test_spill_faults():
    fault_injection.arm("store.spill", "error", count=1)
    spill(b"x")
    assert fault_injection.fired("store.spill") == 1
