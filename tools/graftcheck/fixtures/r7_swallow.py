"""R7 fixture: a daemon pump loop that eats every exception silently.

Never imported — parsed only by graftcheck.
"""


class Pump:
    def __init__(self, queue):
        self._queue = queue
        self._stopped = False

    def _loop(self):
        while not self._stopped:
            fn = self._queue.get()
            try:
                fn()
            except Exception:
                pass               # R7: evidence destroyed
