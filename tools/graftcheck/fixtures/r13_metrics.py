"""R13 positive fixture: one series written as a counter here and a
gauge there (registration is first-wins, the late writer silently
stomps the accumulated value), a get_value read of a series nothing
writes, and two names that collide after Prometheus ``.`` -> ``_``
mangling."""

from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                            record_internal)


def on_request():
    record_internal("app.requests", 1.0, "counter")


def on_scrape():
    # same series, default mtype="gauge": set() replaces the count
    record_internal("app.requests", 0.0)


def dashboard_panel():
    reg = get_metrics_registry()
    # nothing ever writes "app.request_total": silently None forever
    return reg.get_value("app.request_total")


def mangled_pair():
    record_internal("app.rate_limit.hits", 1.0, "counter")
    record_internal("app.rate.limit_hits", 1.0, "counter")
