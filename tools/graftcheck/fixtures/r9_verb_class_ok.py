"""R9 negative contrast: every mutating verb is classified (dedup or
the explicit no-retry registry), pure reads may stay unlisted, and
every set entry names a live verb."""

IDEMPOTENT_VERBS = frozenset({"get_rows"})
DEDUP_VERBS = frozenset({"store_row"})
NO_RETRY_VERBS = frozenset({"drop_row"})


class TableService:
    def __init__(self, server):
        self._rows = {}
        server.register("get_rows", self._handle_get_rows)
        server.register("store_row", self._handle_store_row)
        server.register("drop_row", self._handle_drop_row)
        # Pure read, deliberately unclassified: fine.
        server.register("peek_row", self._handle_peek_row)

    def _handle_get_rows(self, payload):
        return list(self._rows)

    def _handle_store_row(self, payload):
        self._rows[payload["k"]] = payload["v"]
        return True

    def _handle_drop_row(self, payload):
        self._rows.pop(payload["k"], None)
        return True

    def _handle_peek_row(self, payload):
        return self._rows.get(payload["k"])
