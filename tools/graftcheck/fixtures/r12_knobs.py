"""R12 positive fixture: a read of an undeclared knob (AttributeError
in production) AND a declared knob nothing reads (dead — or its
consumer is misspelled, which is the same defect seen from the other
side)."""

from dataclasses import dataclass


@dataclass
class Config:
    flush_interval_s: float = 1.0
    flush_batch_max: int = 64        # declared, never read anywhere


_CONFIG = Config()


def get_config():
    return _CONFIG


def flusher_tick():
    cfg = get_config()
    interval = cfg.flush_interval_s
    # typo'd read: the field is flush_batch_max
    batch = get_config().flush_batch_size
    return interval, batch
