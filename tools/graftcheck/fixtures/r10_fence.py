"""R10 positive fixture: two node-stamped head-bound verbs, only one
of which the head fence-gates — the other would apply a stale
incarnation's send."""


class NodeSide:
    def __init__(self, client):
        self.client = client
        self.node_id = b"n1"
        self.incarnation = 1

    def stamp(self, payload):
        payload["node_id"] = self.node_id
        payload["incarnation"] = self.incarnation
        return payload

    def report(self):
        self.client.call("row_report", self.stamp({"rows": 1}))

    def remove(self):
        # stamped, but the head never gates "row_remove":
        self.client.call("row_remove", self.stamp({"rows": 0}))


class HeadSide:
    def __init__(self):
        self._rows = {}

    def _fence_gate(self, payload, verb):
        if payload.get("incarnation", -1) < 1:
            return {"fenced": True}
        return None

    def _handle_row_report(self, payload):
        fenced = self._fence_gate(payload, "row_report")
        if fenced is not None:
            return fenced
        self._rows["n"] = payload["rows"]
        return True

    def _handle_row_remove(self, payload):
        self._rows.pop("n", None)
        return True
