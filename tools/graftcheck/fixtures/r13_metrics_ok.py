"""R13 negative contrast: one name one type, reads name written
series, no mangling collisions."""

from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                            record_internal)


def on_request():
    record_internal("app.requests", 1.0, "counter")


def on_retry():
    record_internal("app.requests", 1.0, "counter")


def dashboard_panel():
    reg = get_metrics_registry()
    return reg.get_value("app.requests")
