"""R8 fixture: bare threading primitives instead of the diag_*
factories — invisible to the lock-order witness and to contention
profiling.

Never imported — parsed only by graftcheck.
"""

import threading

_MODULE_LOCK = threading.Lock()        # R8: bare module-level Lock


class Manager:
    def __init__(self):
        self._lock = threading.RLock()              # R8: bare RLock
        self._cond = threading.Condition(self._lock)  # R8: bare Condition

    def work(self):
        with self._lock:
            return True
