"""R1 fixture: ABBA lock-order cycle across a call edge.

The shape of the PR-6 deadlock: component A holds its lock and calls
into B (which takes B's lock); B's other path holds B's lock and calls
back into A.  Never imported — parsed only by graftcheck.
"""

import threading


class Store:
    def __init__(self, counter):
        self._lock = threading.Lock()
        self._counter = counter

    def spill_publish(self, oid, url):
        with self._lock:
            # store lock held -> refcount lock taken inside
            self._counter.set_spilled_url(oid, url)

    def delete(self, oid):
        with self._lock:
            pass


class Counter:
    def __init__(self, store: "Store"):
        self._lock = threading.Lock()
        self._store = store

    def set_spilled_url(self, oid, url):
        with self._lock:
            pass

    def on_last_ref_dropped(self, oid):
        with self._lock:
            # refcount lock held -> store lock taken inside: ABBA
            self._store.delete(oid)
