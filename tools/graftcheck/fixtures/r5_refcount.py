"""R5 fixture: a terminal-transition handler that mutates refcounts
before popping its pending entry (so a duplicate/stale completion
double-removes refs), plus an unfloored decrement (so the double-remove
goes negative and total()==0 frees the object under a live ref).

Never imported — parsed only by graftcheck.
"""


class TaskManager:
    def __init__(self):
        self._pending_tasks = {}
        self._counter = None

    def complete_task(self, task_id, returns):
        # R5: refcount mutation precedes the pending pop — the pop is
        # the idempotency gate; a stale second completion re-runs this.
        self._counter.remove_submitted_task_refs(returns)
        entry = self._pending_tasks.pop(task_id, None)
        if entry is None:
            return


class Reference:
    def __init__(self):
        self.local_refs = 1

    def dec(self):
        self.local_refs -= 1     # R5: unfloored decrement
