"""R3 fixture: a registration path aliasing another object's mutable
state — the r6 lost-dispatch root cause (the GCS merge view stored the
raylet's live NodeResources instead of a copy, so a stale usage-poll
write-back erased racing allocate/release calls).

Never imported — parsed only by graftcheck.
"""


class ResourceManager:
    def __init__(self):
        self._views = {}
        self._last = None

    def register_raylet(self, raylet):
        # R3: stores raylet.local_resources itself; any later mutation
        # through self._views writes into the raylet's live ledger.
        self._views[raylet.node_id] = raylet.local_resources
        self._last = raylet.local_resources
