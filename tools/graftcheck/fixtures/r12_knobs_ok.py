"""R12 negative contrast: every read names a declared field, every
field is read."""

from dataclasses import dataclass


@dataclass
class Config:
    flush_interval_s: float = 1.0
    flush_batch_max: int = 64


_CONFIG = Config()


def get_config():
    return _CONFIG


def flusher_tick():
    cfg = get_config()
    return cfg.flush_interval_s, get_config().flush_batch_max
