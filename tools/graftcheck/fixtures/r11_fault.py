"""R11 positive fixture: the arm() string is a typo of the hook()
site's point name — the injection silently tests nothing."""

from ray_tpu._private import fault_injection


def spill(data):
    fault_injection.hook("store.spill")
    return bytes(data)


def test_spill_faults():
    # typo: "store.spil" never fires — a vacuously green chaos test
    fault_injection.arm("store.spil", "error", count=1)
    spill(b"x")
    assert fault_injection.fired("store.spil") == 0
