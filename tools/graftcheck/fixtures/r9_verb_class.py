"""R9 positive fixture: a mutating handler whose verb is missing from
every classification set, plus a ghost entry naming a verb that no
longer exists."""

IDEMPOTENT_VERBS = frozenset({
    "get_rows",
    "renamed_away",     # ghost: nothing registers or calls this verb
})
DEDUP_VERBS = frozenset({"store_row"})


class TableService:
    def __init__(self, server):
        self._rows = {}
        server.register("get_rows", self._handle_get_rows)
        server.register("store_row", self._handle_store_row)
        # MUTATES self._rows but is in no classification set:
        server.register("drop_row", self._handle_drop_row)

    def _handle_get_rows(self, payload):
        return list(self._rows)

    def _handle_store_row(self, payload):
        self._rows[payload["k"]] = payload["v"]
        return True

    def _handle_drop_row(self, payload):
        self._rows.pop(payload["k"], None)
        return True
