"""R4 fixture: a @loop_only method invoked directly from a thread that
is not the event loop (here: an RPC handler), instead of being posted.

Never imported — parsed only by graftcheck.
"""


def loop_only(kind):           # stand-in so the fixture parses stand-alone
    def deco(fn):
        return fn
    return deco


class TaskManager:
    def __init__(self, loop):
        self._loop = loop
        self._queue = []

    @loop_only("raylet")
    def schedule_and_dispatch(self):
        while self._queue:
            self._queue.pop()

    def on_lease_request(self, spec):
        self._queue.append(spec)
        # R4: must be self._loop.post(self.schedule_and_dispatch, ...)
        self.schedule_and_dispatch()
