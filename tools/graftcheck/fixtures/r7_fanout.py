"""R7 fixture (fan-out shape): a listener/callback fan-out loop that
eats a subscriber's exception silently — one broken callback drops
every future notification unseen.

Never imported — parsed only by graftcheck.
"""


class DeathNotifier:
    def __init__(self):
        self._listeners = []

    def notify(self, node_id):
        for cb in list(self._listeners):
            try:
                cb(node_id)
            except Exception:
                pass               # R7 fan-out: per-listener loss, uncounted

    def notify_objects(self, pairs):
        # Attribute-call flavor: listener.on_death(...) counts too.
        for key, listener in pairs:
            try:
                listener.on_death(key)
            except Exception:
                pass               # R7 fan-out


def harmless_per_item_work(items, out):
    # NOT a finding: the try body never calls the loop variable —
    # incidental per-item work is outside the fan-out shape.
    for item in items:
        try:
            out.append(int(str(item)))
        except Exception:
            pass
