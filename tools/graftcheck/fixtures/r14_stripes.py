"""R14 positive fixture: two stripes of one striped lock held on a
single path (nested withs AND a call into a stripe-acquiring method
under a held stripe), plus a stripe name violating the two-digit
[sNN] contract."""

from ray_tpu._private.debug import diag_lock, diag_rlock


class ShardedTable:
    def __init__(self):
        self._stripes = [diag_rlock(f"ShardedTable._lock[s{i:02d}]")
                         for i in range(4)]
        self._rows = [dict() for _ in range(4)]
        # naming violation: un-padded index breaks rollup grouping
        self._extra = diag_lock(f"ShardedTable._aux[s{1}]")

    def _stripe(self, key):
        return self._stripes[hash(key) % 4]

    def move_nested(self, src, dst, key):
        # BAD: second stripe acquired while the first is held
        with self._stripe(src):
            with self._stripe(dst):
                self._rows[hash(dst) % 4][key] = \
                    self._rows[hash(src) % 4].pop(key)

    def move_via_call(self, src, dst, key):
        # BAD: callee takes another stripe under the held one
        with self._stripe(src):
            val = self._rows[hash(src) % 4].pop(key)
            self._put(dst, key, val)

    def _put(self, dst, key, val):
        with self._stripe(dst):
            self._rows[hash(dst) % 4][key] = val
