"""R14 negative contrast: stripes named to the [sNN] contract and
acquired at most one at a time — sequentially for cross-stripe moves,
and one per iteration in the flush loop."""

from ray_tpu._private.debug import diag_rlock


class ShardedTable:
    def __init__(self):
        self._stripes = [diag_rlock(f"ShardedTable._lock[s{i:02d}]")
                         for i in range(4)]
        self._rows = [dict() for _ in range(4)]

    def _stripe(self, key):
        return self._stripes[hash(key) % 4]

    def move_sequential(self, src, dst, key):
        # take, release, then take the other — never both at once
        with self._stripe(src):
            val = self._rows[hash(src) % 4].pop(key)
        with self._stripe(dst):
            self._rows[hash(dst) % 4][key] = val

    def flush_all(self):
        out = []
        for i, stripe in enumerate(self._stripes):
            with stripe:
                out.extend(self._rows[i].items())
                self._rows[i].clear()
        return out
