"""R2 fixture: blocking calls inside a held-lock region.

Never imported — parsed only by graftcheck.
"""

import threading
import time


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()

    def tick(self):
        with self._lock:
            time.sleep(0.5)          # R2: sleep under lock

    def drain(self):
        with self._cond:
            self._cond.wait()        # R2: wait() without timeout
