"""CLI: ``python -m graftcheck [paths...]``.

Exit codes: 0 clean (all findings baselined), 1 findings outside the
baseline, 2 usage / parse failure.  Run from the repo root (a
``graftcheck`` symlink at the root points at ``tools/graftcheck`` so
``-m`` resolves without installing anything).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from graftcheck import analyzer, baseline as baseline_mod, rules


def _repo_root() -> str:
    # tools/graftcheck/__main__.py -> repo root is two levels up from the
    # package dir (symlinked or not, __file__ resolves inside tools/).
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.realpath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="concurrency-invariant static analysis for ray_tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: ray_tpu/)")
    ap.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                    help="baseline file (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--fail-stale", action="store_true",
                    help="also exit non-zero on stale baseline entries "
                         "(the ratchet check used by tests)")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset, e.g. R1,R2")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in rules.ALL_RULES:
            print(f"{rid}: {rules.RULE_TITLES[rid]}")
        return 0

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "ray_tpu")]
    paths = [os.path.abspath(p) for p in paths]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftcheck: no such path: {p}", file=sys.stderr)
            return 2

    selected = {r.strip().upper() for r in args.rules.split(",")
                if r.strip()} or None
    prog, parse_errors = analyzer.load_program(paths, root)
    findings = parse_errors + rules.run_all(prog, paths, root,
                                            rules=selected)

    if args.update_baseline:
        prev = baseline_mod.load(args.baseline)
        baseline_mod.save(args.baseline, findings, prev)
        print(f"graftcheck: wrote {len(findings)} baselined finding(s) "
              f"to {args.baseline}")
        return 0

    base = {} if args.no_baseline else baseline_mod.load(args.baseline)
    new, stale = baseline_mod.split(findings, base)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "baselined": len(findings) - len(new),
            "stale": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"graftcheck: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — "
                  f"remove from {os.path.basename(args.baseline)}):",
                  file=sys.stderr)
            for e in stale:
                print(f"  {e['fingerprint']}  [{e['rule']}] {e['path']} "
                      f"{e['symbol']}", file=sys.stderr)
        print(f"graftcheck: {len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, {len(stale)} stale",
              file=sys.stderr)

    if new:
        return 1
    if stale and args.fail_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
