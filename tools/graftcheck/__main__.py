"""CLI: ``python -m graftcheck [paths...]``.

Exit codes: 0 clean (all findings baselined), 1 findings outside the
baseline, 2 usage / parse failure.  Run from the repo root (a
``graftcheck`` symlink at the root points at ``tools/graftcheck`` so
``-m`` resolves without installing anything).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from graftcheck import analyzer, baseline as baseline_mod, rules


def _repo_root() -> str:
    # tools/graftcheck/__main__.py -> repo root is two levels up from the
    # package dir (symlinked or not, __file__ resolves inside tools/).
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.realpath(__file__))))


def _changed_py_files(root: str):
    """Working-tree .py files changed vs HEAD: unstaged + staged +
    untracked.  The pre-commit fast path — rule passes run only on
    these, while the R9-R14 protocol registries stay whole-repo."""
    import subprocess
    names = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30).stdout
        except (OSError, subprocess.SubprocessError):
            return []
        names.update(out.splitlines())
    return sorted(os.path.join(root, n) for n in names
                  if n.endswith(".py") and
                  os.path.exists(os.path.join(root, n)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="concurrency-invariant static analysis for ray_tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to analyze (default: ray_tpu/)")
    ap.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                    help="baseline file (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--fail-stale", action="store_true",
                    help="also exit non-zero on stale baseline entries "
                         "(the ratchet check used by tests)")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset, e.g. R1,R2")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="RN",
                    help="run a single rule (repeatable; combines with "
                         "--rules)")
    ap.add_argument("--changed-only", action="store_true",
                    help="pre-commit fast path: analyze only files "
                         "changed vs HEAD (git diff + staged + "
                         "untracked); protocol registries (R9-R14) "
                         "stay whole-repo so cross-checks remain "
                         "global, and stale-entry reporting is "
                         "skipped (the subset can't see every "
                         "baselined finding)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in rules.ALL_RULES:
            print(f"{rid}: {rules.RULE_TITLES[rid]}")
        return 0

    root = _repo_root()
    if args.changed_only:
        if args.paths:
            print("graftcheck: --changed-only computes its own file "
                  "set; don't pass paths with it", file=sys.stderr)
            return 2
        paths = _changed_py_files(root)
        if not paths:
            print("graftcheck: 0 new finding(s) (no changed .py files)",
                  file=sys.stderr)
            return 0
    else:
        paths = args.paths or [os.path.join(root, "ray_tpu")]
    paths = [os.path.abspath(p) for p in paths]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftcheck: no such path: {p}", file=sys.stderr)
            return 2

    selected = {r.strip().upper() for r in args.rules.split(",")
                if r.strip()}
    selected |= {r.strip().upper() for r in args.rule if r.strip()}
    selected = selected or None
    prog, parse_errors = analyzer.load_program(paths, root)
    findings = parse_errors + rules.run_all(
        prog, paths, root, rules=selected,
        global_protocol=args.changed_only)

    if args.update_baseline:
        prev = baseline_mod.load(args.baseline)
        baseline_mod.save(args.baseline, findings, prev)
        print(f"graftcheck: wrote {len(findings)} baselined finding(s) "
              f"to {args.baseline}")
        return 0

    base = {} if args.no_baseline else baseline_mod.load(args.baseline)
    new, stale = baseline_mod.split(findings, base)
    if args.changed_only:
        # A diff-scoped run can't see most baselined findings, so every
        # untouched entry would read as "stale" — meaningless here.
        stale = []

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) | {"fingerprint": f.fingerprint} for f in new],
            "baselined": len(findings) - len(new),
            "stale": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"graftcheck: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — "
                  f"remove from {os.path.basename(args.baseline)}):",
                  file=sys.stderr)
            for e in stale:
                print(f"  {e['fingerprint']}  [{e['rule']}] {e['path']} "
                      f"{e['symbol']}", file=sys.stderr)
        print(f"graftcheck: {len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, {len(stale)} stale",
              file=sys.stderr)

    if new:
        return 1
    if stale and args.fail_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
