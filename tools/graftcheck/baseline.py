"""Baseline ratchet: land green, never regress, shrink over time.

The first full run over a living tree surfaces findings that are either
deliberate (a sanctioned blocking call the baseline documents with a
one-line ``why``) or not worth a risky refactor today.  Those are
grandfathered into ``baseline.json`` BY FINGERPRINT (rule + path +
symbol + stable detail — no line numbers, so unrelated edits don't churn
it).  The contract, enforced by ``tests/test_graftcheck.py``:

* a finding NOT in the baseline fails the run (new violations fail);
* a baseline entry with no matching finding is STALE and must be
  removed (removals shrink the baseline — the ratchet only tightens).

``python -m graftcheck --update-baseline`` rewrites the file from the
current findings, preserving existing ``why`` annotations.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from graftcheck.analyzer import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save(path: str, findings: List[Finding],
         previous: Dict[str, dict]) -> None:
    entries = []
    for f in findings:
        prev = previous.get(f.fingerprint, {})
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "why": prev.get("why", "TODO: justify or fix"),
        })
    entries.sort(key=lambda e: (e["rule"], e["path"], e["symbol"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=2)
        f.write("\n")


def split(findings: List[Finding], baseline: Dict[str, dict]
          ) -> Tuple[List[Finding], List[dict]]:
    """(new_findings_not_in_baseline, stale_baseline_entries)."""
    seen = set()
    new = []
    for f in findings:
        if f.fingerprint in baseline:
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return new, stale
