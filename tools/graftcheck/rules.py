"""graftcheck rule passes R1-R7.

Each rule encodes an invariant this repo has already paid for at runtime
(see ISSUE 7 / CHANGES.md):

========  ==============================================================
R1        lock-order graph over `with <lock>:` regions and call edges is
          acyclic (PR-6 ABBA: store lock -> refcount lock vs the spill
          publish path taking them in reverse)
R2        no blocking call while a lock is held: sleeps, waits without a
          timeout, joins, socket/subprocess/file IO, RPC client sends
R3        registration/merge paths must not alias another object's
          mutable containers (the r6 lost-dispatch root cause: the GCS
          stored a raylet's live NodeResources dict)
R4        @loop_only methods are only reached from loop threads: other
          @loop_only code or closures handed to loop.post/schedule_*
R5        terminal-transition idempotency: pop the pending entry before
          mutating refcounts; refcount decrements are floored at zero
R6        no compiled-only code: a .pyc under __pycache__ whose source
          .py is gone is an orphan (this PR replaced two such packages)
R7        no silent exception swallowing in daemon pump loops — use
          ray_tpu._private.debug.swallow.noted(site, exc)
R8        no bare ``threading.Lock/RLock/Condition`` in ray_tpu modules
          — use the ``diag_*`` factories, so every lock joins the
          lock-order witness AND the contention-profiling plane
          (ISSUE 13: a bare lock is invisible to both; new code must
          not silently opt out)
========  ==============================================================
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from graftcheck.analyzer import (LOOP_POST_METHODS, Finding, FunctionModel,
                                 Program, _call_tail, _is_self_attr)

ALL_RULES = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8")

RULE_TITLES = {
    "R1": "lock-order graph must be acyclic",
    "R2": "no blocking calls under a held lock",
    "R3": "no aliased mutable state across components",
    "R4": "@loop_only methods only reached from their event loop",
    "R5": "terminal-transition idempotency / refcount floor hygiene",
    "R6": "no pyc-without-source orphan packages",
    "R7": "no silent exception swallowing in pump loops",
    "R8": "bare threading primitives bypass the diag_* witness plane",
}


# ---------------------------------------------------------------------------
# Shared region walker: statements executed while a given lock is held.


def _walk_lock_regions(prog: Program, fm: FunctionModel, visit):
    """Call ``visit(lock_id, with_node)`` for every `with <lock>` region
    in ``fm``; nested regions are visited with their own id."""

    def rec(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.With):
                lid = None
                for item in child.items:
                    lid = prog.resolve_lock(fm, item.context_expr) or lid
                if lid is not None:
                    visit(lid, child)
            rec(child)

    rec(fm.node)


# ---------------------------------------------------------------------------
# R1 — lock-order graph.


def check_lock_order(prog: Program) -> List[Finding]:
    # edge -> (site_path, site_line, via) provenance of first sighting
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, fm: FunctionModel, line: int, via: str):
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (fm.module.path, line, via)

    self_edges: Dict[str, Tuple[str, int, str]] = {}

    for fm in prog.all_functions():

        def visit(lid: str, with_node: ast.With, fm=fm):
            def scan(node: ast.AST):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(child, ast.With):
                        inner = None
                        for item in child.items:
                            inner = prog.resolve_lock(fm, item.context_expr) \
                                or inner
                        if inner is not None:
                            if inner == lid and \
                                    prog.lock_kinds.get(lid) == "lock":
                                self_edges.setdefault(
                                    lid, (fm.module.path, child.lineno,
                                          fm.qualname))
                            add_edge(lid, inner, fm, child.lineno,
                                     f"nested with in {fm.qualname}")
                            # inner region handled by its own visit()
                    elif isinstance(child, ast.Call):
                        callee = prog.resolve_call(fm, child)
                        if callee is not None:
                            for m in prog.may_acquire(callee):
                                if m == lid and \
                                        prog.lock_kinds.get(lid) == "lock":
                                    self_edges.setdefault(
                                        lid, (fm.module.path, child.lineno,
                                              f"{fm.qualname} -> "
                                              f"{callee.qualname}"))
                                add_edge(lid, m, fm, child.lineno,
                                         f"{fm.qualname} -> "
                                         f"{callee.qualname}")
                    scan(child)

            scan(with_node)

        _walk_lock_regions(prog, fm, visit)

    findings: List[Finding] = []
    for comp in _sccs(edges):
        if len(comp) < 2:
            continue
        nodes = sorted(comp)
        legs = []
        for (a, b), (path, line, via) in sorted(edges.items()):
            if a in comp and b in comp:
                legs.append(f"{a} -> {b} at {path}:{line} ({via})")
        path, line, _ = edges[next(
            (a, b) for (a, b) in edges if a in comp and b in comp)]
        findings.append(Finding(
            rule="R1", path=path, line=line, symbol="lock-graph",
            message=("lock-order cycle: " + " <-> ".join(nodes)
                     + "; edges: " + "; ".join(legs[:6])),
            detail="cycle:" + ",".join(nodes)))
    for lid, (path, line, via) in sorted(self_edges.items()):
        findings.append(Finding(
            rule="R1", path=path, line=line, symbol=via,
            message=(f"non-reentrant lock {lid} may be re-acquired while "
                     f"held (via {via}) — self-deadlock"),
            detail=f"self:{lid}:{via}"))
    return findings


def _sccs(edges: Dict[Tuple[str, str], object]) -> List[Set[str]]:
    succ: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        succ.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strong(v: str):
        work = [(v, iter(succ.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


# ---------------------------------------------------------------------------
# R2 — blocking calls under a held lock.

_SOCKET_BLOCKERS = {"recv", "recv_into", "accept", "sendall", "connect"}
_SUBPROCESS_BLOCKERS = {"run", "call", "check_call", "check_output", "Popen"}


def _blocking_reason(fm: FunctionModel, call: ast.Call) -> Optional[str]:
    func = call.func
    tail = _call_tail(func)
    if tail == "sleep" and isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and fm.module.import_aliases.get(
                func.value.id, func.value.id) == "time":
        return "time.sleep"
    if tail == "wait" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords):
        return "wait() without timeout"
    if tail == "join" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords):
        return "join() without timeout"
    if tail in _SOCKET_BLOCKERS and isinstance(func, ast.Attribute):
        return f"socket .{tail}()"
    if tail in _SUBPROCESS_BLOCKERS and isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and func.value.id == "subprocess":
        return f"subprocess.{tail}"
    if tail == "open" and isinstance(func, ast.Name):
        return "file open()"
    if tail == "call" and isinstance(func, ast.Attribute):
        recv = func.value
        name = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else "")
        if "client" in name or "rpc" in name:
            return f"RPC send via {name}.call()"
    return None


def check_blocking_under_lock(prog: Program) -> List[Finding]:
    findings: List[Finding] = []

    for fm in prog.all_functions():

        def visit(lid: str, with_node: ast.With, fm=fm):
            def scan(node: ast.AST):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(child, ast.Call):
                        reason = _blocking_reason(fm, child)
                        # A cv.wait on the *held* lock's own condition is
                        # the one sanctioned block — but only with a
                        # timeout, which the reason already requires.
                        if reason is not None:
                            findings.append(Finding(
                                rule="R2", path=fm.module.path,
                                line=child.lineno, symbol=fm.qualname,
                                message=(f"blocking call ({reason}) while "
                                         f"holding {lid}"),
                                detail=f"{lid}:{reason}"))
                    scan(child)

            scan(with_node)

        _walk_lock_regions(prog, fm, visit)
    return findings


# ---------------------------------------------------------------------------
# R3 — aliased mutable state across components.

_R3_METHOD_RE = re.compile(
    r"^(register|merge|update|attach|add_|on_|__init__)")
_R3_MUTABLE_ATTR_RE = re.compile(
    r"(resources|available|total|entries|refs|queue|table|buffers?"
    r"|labels|cache|state|stats|view|dict|map)")


def check_aliased_state(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        if not _R3_METHOD_RE.search(fm.node.name):
            continue
        params = {a.arg for a in fm.node.args.args} - {"self"}
        if not params:
            continue
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            stores_on_self = (
                _is_self_attr(tgt) is not None
                or (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and _is_self_attr(tgt.value) is not None))
            if not stores_on_self:
                continue
            rhs = node.value
            if not isinstance(rhs, ast.Attribute):
                continue          # calls (.copy(), dict(...)) are fine
            root = rhs
            while isinstance(root.value, ast.Attribute):
                root = root.value
            if not (isinstance(root.value, ast.Name)
                    and root.value.id in params):
                continue
            if not _R3_MUTABLE_ATTR_RE.search(rhs.attr):
                continue
            findings.append(Finding(
                rule="R3", path=fm.module.path, line=node.lineno,
                symbol=fm.qualname,
                message=(f"stores a reference to "
                         f"{root.value.id}.{rhs.attr} — another "
                         f"object's mutable state; take a .copy() "
                         f"(the r6 lost-dispatch bug was exactly this "
                         f"aliasing)"),
                detail=f"alias:{root.value.id}.{rhs.attr}"))
    return findings


# ---------------------------------------------------------------------------
# R4 — event-loop affinity.


def check_loop_affinity(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    decorated: Dict[str, List[FunctionModel]] = {}
    for fm in prog.all_functions():
        if fm.loop_only_kind:
            decorated.setdefault(fm.node.name, []).append(fm)
    if not decorated:
        return findings
    for fm in prog.all_functions():
        entries = _loop_entry_defs(fm)
        # Lambdas handed directly to loop.post/schedule_* run on the
        # loop thread too: calls inside them are legitimate.
        posted_lambda_calls = set()
        for node in ast.walk(fm.node):
            if isinstance(node, ast.Call) \
                    and _call_tail(node.func) in LOOP_POST_METHODS:
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg):
                            posted_lambda_calls.add(id(sub))
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if tail not in decorated:
                continue
            target = prog.resolve_call(fm, node)
            if target is not None and not target.loop_only_kind:
                continue  # resolved to an undecorated same-name method
            if target is None and not isinstance(node.func, ast.Attribute):
                continue  # bare name that didn't resolve: not a method call
            if fm.loop_only_kind:
                continue
            if id(node) in posted_lambda_calls:
                continue  # inside a lambda handed to loop.post(...)
            encl = _enclosing_def(fm.node, node)
            if encl is not None and encl.name in entries:
                continue  # inside a closure handed to loop.post(...)
            findings.append(Finding(
                rule="R4", path=fm.module.path, line=node.lineno,
                symbol=fm.qualname,
                message=(f"calls @loop_only method {tail}() directly; "
                         f"post it to the loop (loop.post/schedule_*) or "
                         f"mark the caller @loop_only"),
                detail=f"direct-call:{tail}"))
    return findings


def _loop_entry_defs(fm: FunctionModel) -> Set[str]:
    names = set(fm.loop_entry_closures)
    return names


def _enclosing_def(root: ast.AST, needle: ast.AST):
    """Innermost nested FunctionDef containing ``needle`` (None if the
    needle sits directly in ``root``'s own body)."""
    hit = [None]

    def rec(node, current):
        for child in ast.iter_child_nodes(node):
            nxt = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not root:
                nxt = child
            if child is needle:
                hit[0] = nxt
                return True
            if rec(child, nxt):
                return True
        return False

    rec(root, None)
    return hit[0]


# ---------------------------------------------------------------------------
# R5 — terminal-transition idempotency + refcount floors.

_R5_TERMINAL_RE = re.compile(r"^(complete_task|fail_task)$")
_R5_REF_MUTATORS = {"remove_submitted_task_refs", "remove_local_ref"}
_R5_COUNT_ATTR_RE = re.compile(
    r"(^|_)(refs|ref_count|refcount|pin_count|borrowers)($|_)")


def _is_guarded_decrement(fm: FunctionModel, aug: ast.AugAssign) -> bool:
    """True if the decrement sits under an ``if x.attr > 0`` (or ``>=
    1``/``!= 0``) guard on the same attribute — an explicit floor, just
    spelled as a branch instead of ``max(0, ...)``."""
    attr = aug.target.attr

    def guards(test: ast.AST) -> bool:
        for n in ast.walk(test):
            if (isinstance(n, ast.Compare)
                    and isinstance(n.left, ast.Attribute)
                    and n.left.attr == attr):
                return True
        return False

    hit = [False]

    def rec(node, under_guard):
        if node is aug:
            hit[0] = hit[0] or under_guard
            return
        for child in ast.iter_child_nodes(node):
            ug = under_guard or (isinstance(node, ast.If)
                                 and guards(node.test)
                                 and child in node.body)
            rec(child, ug)

    rec(fm.node, False)
    return hit[0]


def check_refcount_hygiene(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        # (b) floor hygiene — anywhere.
        for node in ast.walk(fm.node):
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.target, ast.Attribute)
                    and _R5_COUNT_ATTR_RE.search(node.target.attr)
                    and not _is_guarded_decrement(fm, node)):
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=node.lineno,
                    symbol=fm.qualname,
                    message=(f"unfloored refcount decrement of "
                             f".{node.target.attr} — a duplicate "
                             f"decrement goes negative and frees the "
                             f"object under a live ref; use "
                             f"max(0, x - 1)"),
                    detail=f"floor:{node.target.attr}"))
        # (a) terminal handlers pop pending before touching refcounts.
        if not _R5_TERMINAL_RE.match(fm.node.name):
            continue
        mutations: List[ast.Call] = []
        first_pop_line: Optional[int] = None
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if tail in _R5_REF_MUTATORS:
                mutations.append(node)
            elif tail == "pop" and isinstance(node.func, ast.Attribute):
                recv = node.func.value
                text = recv.attr if isinstance(recv, ast.Attribute) else (
                    recv.id if isinstance(recv, ast.Name) else "")
                if "pending" in text:
                    line = node.lineno
                    if first_pop_line is None or line < first_pop_line:
                        first_pop_line = line
        for call in mutations:
            if first_pop_line is None:
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=call.lineno,
                    symbol=fm.qualname,
                    message=("terminal handler mutates refcounts but never "
                             "pops its pending entry — a duplicate "
                             "terminal transition will double-remove refs"),
                    detail="no-pending-pop"))
            elif call.lineno < first_pop_line:
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=call.lineno,
                    symbol=fm.qualname,
                    message=(f"refcount mutation at line {call.lineno} "
                             f"precedes the pending-entry pop — the pop "
                             f"is the idempotency gate and must come "
                             f"first"),
                    detail="mutation-before-pop"))
    return findings


# ---------------------------------------------------------------------------
# R6 — pyc without source.


def check_pyc_orphans(paths: List[str], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if not os.path.isdir(p):
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != ".git"]
            if os.path.basename(dirpath) != "__pycache__":
                continue
            parent = os.path.dirname(dirpath)
            for fn in sorted(filenames):
                if not fn.endswith(".pyc"):
                    continue
                src = fn.split(".", 1)[0] + ".py"
                if not os.path.exists(os.path.join(parent, src)):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    findings.append(Finding(
                        rule="R6", path=rel, line=0, symbol=src,
                        message=(f"orphaned bytecode: {fn} has no "
                                 f"source {src} next to its __pycache__ "
                                 f"— delete it (a pyc-only package is "
                                 f"unreviewable and untestable)"),
                        detail=f"orphan:{src}"))
    return findings


# ---------------------------------------------------------------------------
# R7 — silent swallow in pump loops and listener/callback fan-outs.


def check_silent_swallow(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        for loop in [n for n in ast.walk(fm.node)
                     if isinstance(n, ast.While)]:
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _is_broad_handler(handler):
                        continue
                    if _is_silent_body(handler.body):
                        findings.append(Finding(
                            rule="R7", path=fm.module.path,
                            line=handler.lineno, symbol=fm.qualname,
                            message=("pump loop swallows exceptions "
                                     "silently; route through "
                                     "debug.swallow.noted(site, exc) so "
                                     "the count and first traceback "
                                     "survive"),
                            detail="silent-swallow"))
        # Listener/callback fan-out shape: ``for cb in listeners: try:
        # cb(...) except: pass``.  Swallowing here is per-LISTENER loss
        # — one buggy subscriber silently stops observing node deaths /
        # events forever (the PR-8 tombstone bug's cousin); the loop
        # must keep fanning out, but the drop has to be counted.
        for loop in [n for n in ast.walk(fm.node)
                     if isinstance(n, ast.For)]:
            targets = _loop_target_names(loop.target)
            if not targets:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                if not _calls_any(node.body, targets):
                    continue
                for handler in node.handlers:
                    if not _is_broad_handler(handler):
                        continue
                    if _is_silent_body(handler.body):
                        findings.append(Finding(
                            rule="R7", path=fm.module.path,
                            line=handler.lineno, symbol=fm.qualname,
                            message=("listener/callback fan-out "
                                     "swallows exceptions silently; a "
                                     "broken subscriber drops every "
                                     "future notification unseen — "
                                     "route through debug.swallow."
                                     "noted(site, exc)"),
                            detail="silent-swallow-fanout"))
    return findings


def _loop_target_names(target: ast.expr) -> Set[str]:
    """Names bound by a for-loop target (``cb`` / ``(key, cb)``)."""
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in target.elts:
            out |= _loop_target_names(el)
        return out
    return set()


def _calls_any(body: List[ast.stmt], names: Set[str]) -> bool:
    """True when the statements CALL one of ``names`` — either directly
    (``cb(...)``) or through an attribute (``listener.on_death(...)``);
    that call is what makes a try/except a fan-out swallow rather than
    incidental per-item work."""
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in names:
                return True
            if isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id in names:
                return True
    return False


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return isinstance(handler.type, ast.Name) and \
        handler.type.id in ("Exception", "BaseException")


def _is_silent_body(body: List[ast.stmt]) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in body)


# ---------------------------------------------------------------------------
# R8 — bare threading primitives outside the diag_* witness plane.

_R8_PRIMITIVES = {"Lock", "RLock", "Condition"}
#: The witness/contention plane itself (and the fault-injection hook it
#: calls into) cannot be built FROM wrapped locks — wrapping would
#: recurse.  Everything else in ray_tpu must route through diag_*.
_R8_EXEMPT_RE = re.compile(
    r"(^|/)_private/debug/|(^|/)_private/fault_injection\.py$")


def check_bare_threading(prog: Program) -> List[Finding]:
    """A ray_tpu module creating ``threading.Lock()/RLock()/
    Condition()`` directly instead of ``diag_lock/diag_rlock/
    diag_condition``: the lock is invisible to the lock-order witness
    AND to contention profiling (ISSUE 13).  Baseline-ratcheted —
    pre-R8 modules are grandfathered with a why; new code cannot
    silently opt out of the plane."""
    findings: List[Finding] = []
    for mod in prog.modules:
        path = mod.path.replace(os.sep, "/")
        if _R8_EXEMPT_RE.search(path):
            continue
        # `from threading import Lock [as L]` — the analyzer's flat
        # alias table loses the source module, so collect the names
        # imported FROM threading here: a bare `Lock()` call through
        # such an import is the trivial R8 bypass.
        from_threading: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for alias in node.names:
                    if alias.name in _R8_PRIMITIVES:
                        from_threading[alias.asname or alias.name] = \
                            alias.name

        def _bare_kind(call: ast.Call, mod=mod,
                       from_threading=from_threading) -> Optional[str]:
            func = call.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _R8_PRIMITIVES \
                    and isinstance(func.value, ast.Name) \
                    and mod.import_aliases.get(
                        func.value.id) == "threading":
                return func.attr
            if isinstance(func, ast.Name):
                return from_threading.get(func.id)
            return None

        def visit(node: ast.AST, qual: List[str], mod=mod):
            for child in ast.iter_child_nodes(node):
                nxt = qual
                if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nxt = qual + [child.name]
                if isinstance(child, ast.Call):
                    kind = _bare_kind(child)
                    if kind is not None:
                        symbol = ".".join(qual[-2:]) or "<module>"
                        factory = {"Lock": "diag_lock",
                                   "RLock": "diag_rlock",
                                   "Condition": "diag_condition"}[kind]
                        findings.append(Finding(
                            rule="R8", path=mod.path, line=child.lineno,
                            symbol=symbol,
                            message=(f"bare threading.{kind}() — "
                                     f"invisible to the lock-order "
                                     f"witness and the contention-"
                                     f"profiling plane; use "
                                     f"debug.{factory}(name)"),
                            detail=f"bare:{kind}"))
                visit(child, nxt)

        visit(mod.tree, [])
    return findings


# ---------------------------------------------------------------------------


def run_all(prog: Program, paths: List[str], repo_root: str,
            rules: Optional[Set[str]] = None) -> List[Finding]:
    selected = rules or set(ALL_RULES)
    findings: List[Finding] = []
    if "R1" in selected:
        findings += check_lock_order(prog)
    if "R2" in selected:
        findings += check_blocking_under_lock(prog)
    if "R3" in selected:
        findings += check_aliased_state(prog)
    if "R4" in selected:
        findings += check_loop_affinity(prog)
    if "R5" in selected:
        findings += check_refcount_hygiene(prog)
    if "R6" in selected:
        # Orphan scan covers the WHOLE repo, not just the analyzed
        # paths: both shipped pyc-only packages lived under tools/ and
        # _private/debug/, which a ray_tpu/-scoped scan would miss.
        findings += check_pyc_orphans([repo_root], repo_root)
    if "R7" in selected:
        findings += check_silent_swallow(prog)
    if "R8" in selected:
        findings += check_bare_threading(prog)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # Two identical defects in one function (e.g. two unfloored
    # decrements of the same attr) must not collapse to one
    # fingerprint — baselining one would silently grandfather both.
    # Suffix repeats with an occurrence index (line order is stable
    # within a function, so the suffix survives unrelated line shifts).
    seen: Dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint
        n = seen.get(fp, 0)
        seen[fp] = n + 1
        if n:
            f.detail = f"{f.detail or f.message}#{n + 1}"
    return findings
