"""graftcheck rule passes R1-R7.

Each rule encodes an invariant this repo has already paid for at runtime
(see ISSUE 7 / CHANGES.md):

========  ==============================================================
R1        lock-order graph over `with <lock>:` regions and call edges is
          acyclic (PR-6 ABBA: store lock -> refcount lock vs the spill
          publish path taking them in reverse)
R2        no blocking call while a lock is held: sleeps, waits without a
          timeout, joins, socket/subprocess/file IO, RPC client sends
R3        registration/merge paths must not alias another object's
          mutable containers (the r6 lost-dispatch root cause: the GCS
          stored a raylet's live NodeResources dict)
R4        @loop_only methods are only reached from loop threads: other
          @loop_only code or closures handed to loop.post/schedule_*
R5        terminal-transition idempotency: pop the pending entry before
          mutating refcounts; refcount decrements are floored at zero
R6        no compiled-only code: a .pyc under __pycache__ whose source
          .py is gone is an orphan (this PR replaced two such packages)
R7        no silent exception swallowing in daemon pump loops — use
          ray_tpu._private.debug.swallow.noted(site, exc)
R8        no bare ``threading.Lock/RLock/Condition`` in ray_tpu modules
          — use the ``diag_*`` factories, so every lock joins the
          lock-order witness AND the contention-profiling plane
          (ISSUE 13: a bare lock is invisible to both; new code must
          not silently opt out)
========  ==============================================================

R9-R14 are the distributed-protocol families (ISSUE 19): they run on
the protocol model extracted by :mod:`graftcheck.protocol` and
cross-check both sides of contracts that PRs 14-18 enforced by
convention only:

========  ==============================================================
R9        every mutating RPC handler's verb is classified in
          ``rpc/verbs.py`` (IDEMPOTENT / DEDUP / CONTROL / NO_RETRY) —
          an unclassified mutating verb silently loses retry+dedup
          protection; also flags classified verbs that no longer exist
R10       every node-stamped head-bound verb passes ``_fence_gate``
          (the remove_partial_location drift this PR fixed: an
          unstamped fire-and-forget removal from a stale incarnation
          could erase a live node's directory row)
R11       every armed fault point (``arm()``/``arm_over_wire()``/
          ``RAY_TPU_FAULT_POINTS``/``fired()``) names a real ``hook()``
          site — a typo'd injection tests nothing, vacuously green
R12       config-knob hygiene: reads through ``get_config()`` name a
          declared Config field, and every declared field is read
          somewhere (or consumed via its RAY_TPU_* env literal)
R13       metric export parity: one name, one type (first-register
          wins silently, so a counter re-recorded as a gauge stomps
          the series); literal ``get_value`` reads name a written
          series; no two names collide after Prometheus ``.``->``_``
R14       stripe discipline: ``Base[sNN]`` two-digit naming contract,
          and at most ONE stripe of a striped lock held per path
          (nested withs, stripe loops under a held stripe, one-level
          calls into stripe-acquiring methods)
========  ==============================================================
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from graftcheck.analyzer import (LOOP_POST_METHODS, Finding, FunctionModel,
                                 Program, _call_tail, _is_self_attr)
from graftcheck.protocol import (ProtocolModel, _fmt_stripe_name,
                                 extract_protocol)

ALL_RULES = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8",
             "R9", "R10", "R11", "R12", "R13", "R14")

#: The protocol-model families (run on graftcheck.protocol registries,
#: not the Program model).
PROTOCOL_RULES = ("R9", "R10", "R11", "R12", "R13", "R14")

RULE_TITLES = {
    "R1": "lock-order graph must be acyclic",
    "R2": "no blocking calls under a held lock",
    "R3": "no aliased mutable state across components",
    "R4": "@loop_only methods only reached from their event loop",
    "R5": "terminal-transition idempotency / refcount floor hygiene",
    "R6": "no pyc-without-source orphan packages",
    "R7": "no silent exception swallowing in pump loops",
    "R8": "bare threading primitives bypass the diag_* witness plane",
    "R9": "mutating RPC verbs must be classified in rpc/verbs.py",
    "R10": "node-stamped head-bound verbs must pass the fence gate",
    "R11": "armed fault points must name a real hook() site",
    "R12": "config knobs: reads declared, declarations read",
    "R13": "metric export parity: one name one type, no dead reads",
    "R14": "stripe locks: [sNN] naming, at most one stripe per path",
}


# ---------------------------------------------------------------------------
# Shared region walker: statements executed while a given lock is held.


def _walk_lock_regions(prog: Program, fm: FunctionModel, visit):
    """Call ``visit(lock_id, with_node)`` for every `with <lock>` region
    in ``fm``; nested regions are visited with their own id."""

    def rec(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.With):
                lid = None
                for item in child.items:
                    lid = prog.resolve_lock(fm, item.context_expr) or lid
                if lid is not None:
                    visit(lid, child)
            rec(child)

    rec(fm.node)


# ---------------------------------------------------------------------------
# R1 — lock-order graph.


def check_lock_order(prog: Program) -> List[Finding]:
    # edge -> (site_path, site_line, via) provenance of first sighting
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, fm: FunctionModel, line: int, via: str):
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (fm.module.path, line, via)

    self_edges: Dict[str, Tuple[str, int, str]] = {}

    for fm in prog.all_functions():

        def visit(lid: str, with_node: ast.With, fm=fm):
            def scan(node: ast.AST):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(child, ast.With):
                        inner = None
                        for item in child.items:
                            inner = prog.resolve_lock(fm, item.context_expr) \
                                or inner
                        if inner is not None:
                            if inner == lid and \
                                    prog.lock_kinds.get(lid) == "lock":
                                self_edges.setdefault(
                                    lid, (fm.module.path, child.lineno,
                                          fm.qualname))
                            add_edge(lid, inner, fm, child.lineno,
                                     f"nested with in {fm.qualname}")
                            # inner region handled by its own visit()
                    elif isinstance(child, ast.Call):
                        callee = prog.resolve_call(fm, child)
                        if callee is not None:
                            for m in prog.may_acquire(callee):
                                if m == lid and \
                                        prog.lock_kinds.get(lid) == "lock":
                                    self_edges.setdefault(
                                        lid, (fm.module.path, child.lineno,
                                              f"{fm.qualname} -> "
                                              f"{callee.qualname}"))
                                add_edge(lid, m, fm, child.lineno,
                                         f"{fm.qualname} -> "
                                         f"{callee.qualname}")
                    scan(child)

            scan(with_node)

        _walk_lock_regions(prog, fm, visit)

    findings: List[Finding] = []
    for comp in _sccs(edges):
        if len(comp) < 2:
            continue
        nodes = sorted(comp)
        legs = []
        for (a, b), (path, line, via) in sorted(edges.items()):
            if a in comp and b in comp:
                legs.append(f"{a} -> {b} at {path}:{line} ({via})")
        path, line, _ = edges[next(
            (a, b) for (a, b) in edges if a in comp and b in comp)]
        findings.append(Finding(
            rule="R1", path=path, line=line, symbol="lock-graph",
            message=("lock-order cycle: " + " <-> ".join(nodes)
                     + "; edges: " + "; ".join(legs[:6])),
            detail="cycle:" + ",".join(nodes)))
    for lid, (path, line, via) in sorted(self_edges.items()):
        findings.append(Finding(
            rule="R1", path=path, line=line, symbol=via,
            message=(f"non-reentrant lock {lid} may be re-acquired while "
                     f"held (via {via}) — self-deadlock"),
            detail=f"self:{lid}:{via}"))
    return findings


def _sccs(edges: Dict[Tuple[str, str], object]) -> List[Set[str]]:
    succ: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        succ.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strong(v: str):
        work = [(v, iter(succ.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


# ---------------------------------------------------------------------------
# R2 — blocking calls under a held lock.

_SOCKET_BLOCKERS = {"recv", "recv_into", "accept", "sendall", "connect"}
_SUBPROCESS_BLOCKERS = {"run", "call", "check_call", "check_output", "Popen"}


def _blocking_reason(fm: FunctionModel, call: ast.Call) -> Optional[str]:
    func = call.func
    tail = _call_tail(func)
    if tail == "sleep" and isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and fm.module.import_aliases.get(
                func.value.id, func.value.id) == "time":
        return "time.sleep"
    if tail == "wait" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords):
        return "wait() without timeout"
    if tail == "join" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords):
        return "join() without timeout"
    if tail in _SOCKET_BLOCKERS and isinstance(func, ast.Attribute):
        return f"socket .{tail}()"
    if tail in _SUBPROCESS_BLOCKERS and isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and func.value.id == "subprocess":
        return f"subprocess.{tail}"
    if tail == "open" and isinstance(func, ast.Name):
        return "file open()"
    if tail == "call" and isinstance(func, ast.Attribute):
        recv = func.value
        name = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else "")
        if "client" in name or "rpc" in name:
            return f"RPC send via {name}.call()"
    return None


def check_blocking_under_lock(prog: Program) -> List[Finding]:
    findings: List[Finding] = []

    for fm in prog.all_functions():

        def visit(lid: str, with_node: ast.With, fm=fm):
            def scan(node: ast.AST):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(child, ast.Call):
                        reason = _blocking_reason(fm, child)
                        # A cv.wait on the *held* lock's own condition is
                        # the one sanctioned block — but only with a
                        # timeout, which the reason already requires.
                        if reason is not None:
                            findings.append(Finding(
                                rule="R2", path=fm.module.path,
                                line=child.lineno, symbol=fm.qualname,
                                message=(f"blocking call ({reason}) while "
                                         f"holding {lid}"),
                                detail=f"{lid}:{reason}"))
                    scan(child)

            scan(with_node)

        _walk_lock_regions(prog, fm, visit)
    return findings


# ---------------------------------------------------------------------------
# R3 — aliased mutable state across components.

_R3_METHOD_RE = re.compile(
    r"^(register|merge|update|attach|add_|on_|__init__)")
_R3_MUTABLE_ATTR_RE = re.compile(
    r"(resources|available|total|entries|refs|queue|table|buffers?"
    r"|labels|cache|state|stats|view|dict|map)")


def check_aliased_state(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        if not _R3_METHOD_RE.search(fm.node.name):
            continue
        params = {a.arg for a in fm.node.args.args} - {"self"}
        if not params:
            continue
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            stores_on_self = (
                _is_self_attr(tgt) is not None
                or (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and _is_self_attr(tgt.value) is not None))
            if not stores_on_self:
                continue
            rhs = node.value
            if not isinstance(rhs, ast.Attribute):
                continue          # calls (.copy(), dict(...)) are fine
            root = rhs
            while isinstance(root.value, ast.Attribute):
                root = root.value
            if not (isinstance(root.value, ast.Name)
                    and root.value.id in params):
                continue
            if not _R3_MUTABLE_ATTR_RE.search(rhs.attr):
                continue
            findings.append(Finding(
                rule="R3", path=fm.module.path, line=node.lineno,
                symbol=fm.qualname,
                message=(f"stores a reference to "
                         f"{root.value.id}.{rhs.attr} — another "
                         f"object's mutable state; take a .copy() "
                         f"(the r6 lost-dispatch bug was exactly this "
                         f"aliasing)"),
                detail=f"alias:{root.value.id}.{rhs.attr}"))
    return findings


# ---------------------------------------------------------------------------
# R4 — event-loop affinity.


def check_loop_affinity(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    decorated: Dict[str, List[FunctionModel]] = {}
    for fm in prog.all_functions():
        if fm.loop_only_kind:
            decorated.setdefault(fm.node.name, []).append(fm)
    if not decorated:
        return findings
    for fm in prog.all_functions():
        entries = _loop_entry_defs(fm)
        # Lambdas handed directly to loop.post/schedule_* run on the
        # loop thread too: calls inside them are legitimate.
        posted_lambda_calls = set()
        for node in ast.walk(fm.node):
            if isinstance(node, ast.Call) \
                    and _call_tail(node.func) in LOOP_POST_METHODS:
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg):
                            posted_lambda_calls.add(id(sub))
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if tail not in decorated:
                continue
            target = prog.resolve_call(fm, node)
            if target is not None and not target.loop_only_kind:
                continue  # resolved to an undecorated same-name method
            if target is None and not isinstance(node.func, ast.Attribute):
                continue  # bare name that didn't resolve: not a method call
            if fm.loop_only_kind:
                continue
            if id(node) in posted_lambda_calls:
                continue  # inside a lambda handed to loop.post(...)
            encl = _enclosing_def(fm.node, node)
            if encl is not None and encl.name in entries:
                continue  # inside a closure handed to loop.post(...)
            findings.append(Finding(
                rule="R4", path=fm.module.path, line=node.lineno,
                symbol=fm.qualname,
                message=(f"calls @loop_only method {tail}() directly; "
                         f"post it to the loop (loop.post/schedule_*) or "
                         f"mark the caller @loop_only"),
                detail=f"direct-call:{tail}"))
    return findings


def _loop_entry_defs(fm: FunctionModel) -> Set[str]:
    names = set(fm.loop_entry_closures)
    return names


def _enclosing_def(root: ast.AST, needle: ast.AST):
    """Innermost nested FunctionDef containing ``needle`` (None if the
    needle sits directly in ``root``'s own body)."""
    hit = [None]

    def rec(node, current):
        for child in ast.iter_child_nodes(node):
            nxt = current
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not root:
                nxt = child
            if child is needle:
                hit[0] = nxt
                return True
            if rec(child, nxt):
                return True
        return False

    rec(root, None)
    return hit[0]


# ---------------------------------------------------------------------------
# R5 — terminal-transition idempotency + refcount floors.

_R5_TERMINAL_RE = re.compile(r"^(complete_task|fail_task)$")
_R5_REF_MUTATORS = {"remove_submitted_task_refs", "remove_local_ref"}
_R5_COUNT_ATTR_RE = re.compile(
    r"(^|_)(refs|ref_count|refcount|pin_count|borrowers)($|_)")


def _is_guarded_decrement(fm: FunctionModel, aug: ast.AugAssign) -> bool:
    """True if the decrement sits under an ``if x.attr > 0`` (or ``>=
    1``/``!= 0``) guard on the same attribute — an explicit floor, just
    spelled as a branch instead of ``max(0, ...)``."""
    attr = aug.target.attr

    def guards(test: ast.AST) -> bool:
        for n in ast.walk(test):
            if (isinstance(n, ast.Compare)
                    and isinstance(n.left, ast.Attribute)
                    and n.left.attr == attr):
                return True
        return False

    hit = [False]

    def rec(node, under_guard):
        if node is aug:
            hit[0] = hit[0] or under_guard
            return
        for child in ast.iter_child_nodes(node):
            ug = under_guard or (isinstance(node, ast.If)
                                 and guards(node.test)
                                 and child in node.body)
            rec(child, ug)

    rec(fm.node, False)
    return hit[0]


def check_refcount_hygiene(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        # (b) floor hygiene — anywhere.
        for node in ast.walk(fm.node):
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.target, ast.Attribute)
                    and _R5_COUNT_ATTR_RE.search(node.target.attr)
                    and not _is_guarded_decrement(fm, node)):
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=node.lineno,
                    symbol=fm.qualname,
                    message=(f"unfloored refcount decrement of "
                             f".{node.target.attr} — a duplicate "
                             f"decrement goes negative and frees the "
                             f"object under a live ref; use "
                             f"max(0, x - 1)"),
                    detail=f"floor:{node.target.attr}"))
        # (a) terminal handlers pop pending before touching refcounts.
        if not _R5_TERMINAL_RE.match(fm.node.name):
            continue
        mutations: List[ast.Call] = []
        first_pop_line: Optional[int] = None
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if tail in _R5_REF_MUTATORS:
                mutations.append(node)
            elif tail == "pop" and isinstance(node.func, ast.Attribute):
                recv = node.func.value
                text = recv.attr if isinstance(recv, ast.Attribute) else (
                    recv.id if isinstance(recv, ast.Name) else "")
                if "pending" in text:
                    line = node.lineno
                    if first_pop_line is None or line < first_pop_line:
                        first_pop_line = line
        for call in mutations:
            if first_pop_line is None:
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=call.lineno,
                    symbol=fm.qualname,
                    message=("terminal handler mutates refcounts but never "
                             "pops its pending entry — a duplicate "
                             "terminal transition will double-remove refs"),
                    detail="no-pending-pop"))
            elif call.lineno < first_pop_line:
                findings.append(Finding(
                    rule="R5", path=fm.module.path, line=call.lineno,
                    symbol=fm.qualname,
                    message=(f"refcount mutation at line {call.lineno} "
                             f"precedes the pending-entry pop — the pop "
                             f"is the idempotency gate and must come "
                             f"first"),
                    detail="mutation-before-pop"))
    return findings


# ---------------------------------------------------------------------------
# R6 — pyc without source.


def check_pyc_orphans(paths: List[str], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if not os.path.isdir(p):
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != ".git"]
            if os.path.basename(dirpath) != "__pycache__":
                continue
            parent = os.path.dirname(dirpath)
            for fn in sorted(filenames):
                if not fn.endswith(".pyc"):
                    continue
                src = fn.split(".", 1)[0] + ".py"
                if not os.path.exists(os.path.join(parent, src)):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          repo_root)
                    findings.append(Finding(
                        rule="R6", path=rel, line=0, symbol=src,
                        message=(f"orphaned bytecode: {fn} has no "
                                 f"source {src} next to its __pycache__ "
                                 f"— delete it (a pyc-only package is "
                                 f"unreviewable and untestable)"),
                        detail=f"orphan:{src}"))
    return findings


# ---------------------------------------------------------------------------
# R7 — silent swallow in pump loops and listener/callback fan-outs.


def check_silent_swallow(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for fm in prog.all_functions():
        for loop in [n for n in ast.walk(fm.node)
                     if isinstance(n, ast.While)]:
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _is_broad_handler(handler):
                        continue
                    if _is_silent_body(handler.body):
                        findings.append(Finding(
                            rule="R7", path=fm.module.path,
                            line=handler.lineno, symbol=fm.qualname,
                            message=("pump loop swallows exceptions "
                                     "silently; route through "
                                     "debug.swallow.noted(site, exc) so "
                                     "the count and first traceback "
                                     "survive"),
                            detail="silent-swallow"))
        # Listener/callback fan-out shape: ``for cb in listeners: try:
        # cb(...) except: pass``.  Swallowing here is per-LISTENER loss
        # — one buggy subscriber silently stops observing node deaths /
        # events forever (the PR-8 tombstone bug's cousin); the loop
        # must keep fanning out, but the drop has to be counted.
        for loop in [n for n in ast.walk(fm.node)
                     if isinstance(n, ast.For)]:
            targets = _loop_target_names(loop.target)
            if not targets:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                if not _calls_any(node.body, targets):
                    continue
                for handler in node.handlers:
                    if not _is_broad_handler(handler):
                        continue
                    if _is_silent_body(handler.body):
                        findings.append(Finding(
                            rule="R7", path=fm.module.path,
                            line=handler.lineno, symbol=fm.qualname,
                            message=("listener/callback fan-out "
                                     "swallows exceptions silently; a "
                                     "broken subscriber drops every "
                                     "future notification unseen — "
                                     "route through debug.swallow."
                                     "noted(site, exc)"),
                            detail="silent-swallow-fanout"))
    return findings


def _loop_target_names(target: ast.expr) -> Set[str]:
    """Names bound by a for-loop target (``cb`` / ``(key, cb)``)."""
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in target.elts:
            out |= _loop_target_names(el)
        return out
    return set()


def _calls_any(body: List[ast.stmt], names: Set[str]) -> bool:
    """True when the statements CALL one of ``names`` — either directly
    (``cb(...)``) or through an attribute (``listener.on_death(...)``);
    that call is what makes a try/except a fan-out swallow rather than
    incidental per-item work."""
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in names:
                return True
            if isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id in names:
                return True
    return False


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return isinstance(handler.type, ast.Name) and \
        handler.type.id in ("Exception", "BaseException")


def _is_silent_body(body: List[ast.stmt]) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in body)


# ---------------------------------------------------------------------------
# R8 — bare threading primitives outside the diag_* witness plane.

_R8_PRIMITIVES = {"Lock", "RLock", "Condition"}
#: The witness/contention plane itself (and the fault-injection hook it
#: calls into) cannot be built FROM wrapped locks — wrapping would
#: recurse.  Everything else in ray_tpu must route through diag_*.
_R8_EXEMPT_RE = re.compile(
    r"(^|/)_private/debug/|(^|/)_private/fault_injection\.py$")


def check_bare_threading(prog: Program) -> List[Finding]:
    """A ray_tpu module creating ``threading.Lock()/RLock()/
    Condition()`` directly instead of ``diag_lock/diag_rlock/
    diag_condition``: the lock is invisible to the lock-order witness
    AND to contention profiling (ISSUE 13).  Baseline-ratcheted —
    pre-R8 modules are grandfathered with a why; new code cannot
    silently opt out of the plane."""
    findings: List[Finding] = []
    for mod in prog.modules:
        path = mod.path.replace(os.sep, "/")
        if _R8_EXEMPT_RE.search(path):
            continue
        # `from threading import Lock [as L]` — the analyzer's flat
        # alias table loses the source module, so collect the names
        # imported FROM threading here: a bare `Lock()` call through
        # such an import is the trivial R8 bypass.
        from_threading: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for alias in node.names:
                    if alias.name in _R8_PRIMITIVES:
                        from_threading[alias.asname or alias.name] = \
                            alias.name

        def _bare_kind(call: ast.Call, mod=mod,
                       from_threading=from_threading) -> Optional[str]:
            func = call.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _R8_PRIMITIVES \
                    and isinstance(func.value, ast.Name) \
                    and mod.import_aliases.get(
                        func.value.id) == "threading":
                return func.attr
            if isinstance(func, ast.Name):
                return from_threading.get(func.id)
            return None

        def visit(node: ast.AST, qual: List[str], mod=mod):
            for child in ast.iter_child_nodes(node):
                nxt = qual
                if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nxt = qual + [child.name]
                if isinstance(child, ast.Call):
                    kind = _bare_kind(child)
                    if kind is not None:
                        symbol = ".".join(qual[-2:]) or "<module>"
                        factory = {"Lock": "diag_lock",
                                   "RLock": "diag_rlock",
                                   "Condition": "diag_condition"}[kind]
                        findings.append(Finding(
                            rule="R8", path=mod.path, line=child.lineno,
                            symbol=symbol,
                            message=(f"bare threading.{kind}() — "
                                     f"invisible to the lock-order "
                                     f"witness and the contention-"
                                     f"profiling plane; use "
                                     f"debug.{factory}(name)"),
                            detail=f"bare:{kind}"))
                visit(child, nxt)

        visit(mod.tree, [])
    return findings


# ---------------------------------------------------------------------------
# R9 — unclassified mutating verbs.

#: container mutators: a call to one of these on a self-rooted chain
#: counts as a state mutation.
_MUTATOR_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "extend", "insert", "setdefault",
}


def _self_rooted(expr: ast.AST) -> bool:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return isinstance(expr, ast.Name) and expr.id == "self"


def _func_mutates(fn: ast.AST) -> bool:
    """Direct self-state mutation: assignment/del through a self-rooted
    chain, or a container mutator called on one."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                        _self_rooted(t):
                    return True
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                        _self_rooted(t):
                    return True
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS and \
                _self_rooted(node.func.value):
            return True
    return False


def _handler_mutates(fn: ast.AST, cls: Optional[ast.ClassDef],
                     depth: int = 3,
                     seen: Optional[Set[str]] = None) -> bool:
    """Transitive (same-class, depth-limited) may-mutate for a handler."""
    if _func_mutates(fn):
        return True
    if depth <= 0 or cls is None:
        return False
    seen = seen or {getattr(fn, "name", "")}
    methods = {item.name: item for item in cls.body
               if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            name = node.func.attr
            if name in methods and name not in seen:
                seen.add(name)
                if _handler_mutates(methods[name], cls, depth - 1, seen):
                    return True
    return False


def check_verb_classification(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    classified: Set[str] = set()
    for s in proto.verb_sets.values():
        classified |= s
    if not proto.verb_sets:
        # No classification registry in the analyzed set (single-file
        # run on a module with no verb sets): nothing to check against.
        return findings
    for verb in sorted(proto.server_verbs):
        if verb in classified:
            continue
        for h in proto.server_verbs[verb]:
            if h.func is None or not _handler_mutates(h.func, h.cls):
                continue
            findings.append(Finding(
                rule="R9", path=h.site.path, line=h.site.line,
                symbol=h.site.symbol,
                message=(f"verb {verb!r} mutates state but is not "
                         f"classified in rpc/verbs.py (IDEMPOTENT / "
                         f"DEDUP / CONTROL / NO_RETRY) — it silently "
                         f"gets no retry or dedup protection"),
                detail=f"unclassified:{verb}"))
            break
    # Ghost classifications: a set entry naming a verb that is neither
    # registered nor called is a typo waiting to mis-protect a rename.
    known = set(proto.server_verbs) | set(proto.client_verbs)
    for set_name, verbs in sorted(proto.verb_sets.items()):
        site = proto.verb_set_sites.get(set_name)
        if site is None:
            continue
        for verb in sorted(verbs - known):
            findings.append(Finding(
                rule="R9", path=site.path, line=site.line,
                symbol=set_name,
                message=(f"{set_name} lists verb {verb!r} but no "
                         f"handler registration or call site exists — "
                         f"stale or typo'd classification"),
                detail=f"ghost:{verb}"))
    return findings


# ---------------------------------------------------------------------------
# R10 — fence-gate coverage.


def check_fence_coverage(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    if not proto.stamped_verbs and not proto.gated_verbs:
        return findings
    control = proto.verb_sets.get("CONTROL_VERBS", set())
    for verb in sorted(proto.stamped_verbs):
        if verb in proto.gated_verbs or verb in control:
            continue
        site = proto.stamped_verbs[verb][0]
        findings.append(Finding(
            rule="R10", path=site.path, line=site.line,
            symbol=site.symbol,
            message=(f"verb {verb!r} is sent with a stamp()ed payload "
                     f"but the head handler never calls "
                     f"_fence_gate(payload, {verb!r}) — a stale "
                     f"incarnation's send would be applied"),
            detail=f"unfenced:{verb}"))
    for verb in sorted(proto.gated_verbs):
        if verb in proto.stamped_verbs:
            continue
        site = proto.gated_verbs[verb][0]
        findings.append(Finding(
            rule="R10", path=site.path, line=site.line,
            symbol=site.symbol,
            message=(f"_fence_gate checks verb {verb!r} but no client "
                     f"site stamps that verb — the gate is dead code "
                     f"or the sender forgot stamp()"),
            detail=f"gate_stale:{verb}"))
    return findings


# ---------------------------------------------------------------------------
# R11 — fault-point liveness.


def check_fault_liveness(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    if not proto.armed_points:
        return findings
    for point in sorted(proto.armed_points):
        if point in proto.hook_points:
            continue
        for site in proto.armed_points[point]:
            findings.append(Finding(
                rule="R11", path=site.path, line=site.line,
                symbol=site.symbol,
                message=(f"fault point {point!r} is armed/asserted but "
                         f"no fault_injection.hook({point!r}) site "
                         f"exists — the injection silently tests "
                         f"nothing"),
                detail=f"dead_point:{point}"))
            break
    return findings


# ---------------------------------------------------------------------------
# R12 — config-knob hygiene.


def check_knob_hygiene(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    if not proto.config_fields:
        return findings
    for attr in sorted(proto.config_reads):
        if attr in proto.config_fields or attr in proto.config_methods:
            continue
        site = proto.config_reads[attr][0]
        findings.append(Finding(
            rule="R12", path=site.path, line=site.line,
            symbol=site.symbol,
            message=(f"get_config().{attr} is read but Config declares "
                     f"no field {attr!r} — AttributeError at runtime, "
                     f"or a renamed knob left a stale reader"),
            detail=f"undeclared_knob:{attr}"))
    for name, site in sorted(proto.config_fields.items()):
        if name in proto.config_reads or name in proto.config_reads_loose:
            continue
        if f"RAY_TPU_{name.upper()}" in proto.env_literals:
            continue
        findings.append(Finding(
            rule="R12", path=site.path, line=site.line,
            symbol="Config",
            message=(f"Config field {name!r} is declared but never "
                     f"read through get_config() (nor via its "
                     f"RAY_TPU_* env literal) — a dead knob, or the "
                     f"consumer reads a misspelled name"),
            detail=f"dead_knob:{name}"))
    return findings


# ---------------------------------------------------------------------------
# R13 — metric export parity.


def check_metric_parity(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(proto.metric_writes):
        entries = proto.metric_writes[name]
        types = sorted({t for _s, t in entries})
        if len(types) > 1:
            # register() is first-wins and record_internal branches on
            # its OWN mtype argument: the gauge-writer of a counter
            # series overwrites the accumulated value in place.
            site = entries[1][0]
            findings.append(Finding(
                rule="R13", path=site.path, line=site.line,
                symbol=site.symbol,
                message=(f"metric {name!r} is written with conflicting "
                         f"types {types} — registration is first-wins, "
                         f"so the late writer silently corrupts the "
                         f"series"),
                detail=f"metric_type_conflict:{name}:{'/'.join(types)}"))
    if proto.metric_writes or proto.metric_reads:
        for name in sorted(proto.metric_reads):
            if name in proto.metric_writes:
                continue
            site = proto.metric_reads[name][0]
            findings.append(Finding(
                rule="R13", path=site.path, line=site.line,
                symbol=site.symbol,
                message=(f"get_value({name!r}) reads a series no site "
                         f"ever writes — get_value returns None "
                         f"silently, so the read is vacuous"),
                detail=f"dead_metric_read:{name}"))
    by_mangled: Dict[str, Set[str]] = {}
    for name in proto.metric_writes:
        by_mangled.setdefault(name.replace(".", "_"), set()).add(name)
    for pname, names in sorted(by_mangled.items()):
        if len(names) > 1:
            first = sorted(names)[0]
            site = proto.metric_writes[first][0][0]
            findings.append(Finding(
                rule="R13", path=site.path, line=site.line,
                symbol=site.symbol,
                message=(f"metric names {sorted(names)} all render as "
                         f"Prometheus family {pname!r} — exposition "
                         f"merges unrelated series"),
                detail=f"mangle_collision:{pname}"))
    return findings


# ---------------------------------------------------------------------------
# R14 — stripe naming + at-most-one-stripe discipline.


def _expr_touches(expr: ast.AST, containers: Dict[str, str],
                  accessors: Dict[str, str],
                  loop_bindings: Dict[str, str]) -> Set[str]:
    """Stripe families an expression may select a stripe of."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in containers \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            out.add(containers[node.attr])
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in accessors and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            out.add(accessors[node.func.attr])
        elif isinstance(node, ast.Name) and node.id in loop_bindings:
            out.add(loop_bindings[node.id])
    return out


def _method_acquires(fn: ast.AST, containers, accessors) -> Set[str]:
    """Families this method acquires a stripe of via any `with`."""
    loop_bindings = _loop_bindings(fn, containers)
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                out |= _expr_touches(item.context_expr, containers,
                                     accessors, loop_bindings)
    return out


def _loop_bindings(fn: ast.AST, containers: Dict[str, str]) -> Dict[str, str]:
    """``for s in self._stripes:`` binds ``s`` to the family."""
    bindings: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            fams = _expr_touches(node.iter, containers, {}, {})
            if fams:
                bindings[node.target.id] = sorted(fams)[0]
    return bindings


def check_stripe_discipline(proto: ProtocolModel) -> List[Finding]:
    findings: List[Finding] = []
    for site, text in proto.stripe_name_violations:
        findings.append(Finding(
            rule="R14", path=site.path, line=site.line,
            symbol=site.symbol,
            message=(f"stripe-like lock name {text!r} violates the "
                     f"PR 17 naming contract: stripes must end in "
                     f"[sNN] (two-digit index, e.g. "
                     f"'Base._lock[s{{i:02d}}]')"),
            detail=f"stripe_name:{text}"))
    if not proto.stripe_families:
        return findings
    stripe_classes: Dict[str, str] = {}
    for fam in proto.stripe_families.values():
        for cname in fam.stripe_classes:
            stripe_classes[cname] = fam.base
    direct_fams = {f.base for f in proto.stripe_families.values()
                   if f.direct}

    for rel, tree in proto.trees:
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            containers: Dict[str, str] = {}
            # self.X = [...] whose element expr constructs a stripe
            # (stripe class call, or a direct diag_* stripe name)
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute) and
                            isinstance(t.value, ast.Name) and
                            t.value.id == "self"):
                        continue
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call):
                            cname = None
                            if isinstance(sub.func, ast.Name):
                                cname = sub.func.id
                            elif isinstance(sub.func, ast.Attribute):
                                cname = sub.func.attr
                            if cname in stripe_classes:
                                containers[t.attr] = stripe_classes[cname]
                            elif cname in ("diag_lock", "diag_rlock",
                                           "diag_condition"):
                                for a in sub.args:
                                    txt = _fmt_stripe_name(a)
                                    if txt and "[s" in txt:
                                        base = txt[:txt.rindex("[s")]
                                        if base in direct_fams:
                                            containers[t.attr] = base
            if not containers:
                continue
            methods = {item.name: item for item in cls.body
                       if isinstance(item, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            # accessor methods: return self.<container>[...]
            accessors: Dict[str, str] = {}
            for mname, fn in methods.items():
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) and \
                            node.value is not None:
                        fams = _expr_touches(node.value, containers,
                                             {}, {})
                        if fams:
                            accessors[mname] = sorted(fams)[0]
            acquires = {mname: _method_acquires(fn, containers, accessors)
                        for mname, fn in methods.items()}

            for mname, fn in methods.items():
                loop_bindings = _loop_bindings(fn, containers)
                qual = f"{cls.name}.{mname}"

                def walk(node, held: Set[str]):
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                            continue
                        entered = held
                        if isinstance(child, ast.With):
                            fams = set()
                            for item in child.items:
                                fams |= _expr_touches(
                                    item.context_expr, containers,
                                    accessors, loop_bindings)
                            overlap = fams & held
                            if overlap:
                                fam = sorted(overlap)[0]
                                findings.append(Finding(
                                    rule="R14", path=rel,
                                    line=child.lineno, symbol=qual,
                                    message=(
                                        f"acquires a second stripe of "
                                        f"{fam!r} while one is already "
                                        f"held — the at-most-one-"
                                        f"stripe discipline makes "
                                        f"stripe order deadlock-free; "
                                        f"two held stripes reintroduce "
                                        f"ABBA"),
                                    detail=f"stripe_nest:{fam}:{qual}"))
                            entered = held | fams
                        elif isinstance(child, ast.Call) and held and \
                                isinstance(child.func, ast.Attribute) \
                                and isinstance(child.func.value,
                                               ast.Name) and \
                                child.func.value.id == "self":
                            callee = child.func.attr
                            inner = acquires.get(callee, set()) & held
                            if inner and callee != mname:
                                fam = sorted(inner)[0]
                                findings.append(Finding(
                                    rule="R14", path=rel,
                                    line=child.lineno, symbol=qual,
                                    message=(
                                        f"calls self.{callee}() — "
                                        f"which acquires a {fam!r} "
                                        f"stripe — while already "
                                        f"holding one: two stripes of "
                                        f"one striped lock on a single "
                                        f"path"),
                                    detail=(f"stripe_call:{fam}:{qual}"
                                            f"->{callee}")))
                        walk(child, entered)

                walk(fn, set())
    return findings


# ---------------------------------------------------------------------------


def run_protocol_rules(proto: ProtocolModel,
                       selected: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    if "R9" in selected:
        findings += check_verb_classification(proto)
    if "R10" in selected:
        findings += check_fence_coverage(proto)
    if "R11" in selected:
        findings += check_fault_liveness(proto)
    if "R12" in selected:
        findings += check_knob_hygiene(proto)
    if "R13" in selected:
        findings += check_metric_parity(proto)
    if "R14" in selected:
        findings += check_stripe_discipline(proto)
    return findings


def run_all(prog: Program, paths: List[str], repo_root: str,
            rules: Optional[Set[str]] = None,
            global_protocol: bool = False) -> List[Finding]:
    """Run ``rules`` (default all) over the loaded ``prog``.

    ``global_protocol=True`` (the --changed-only fast path) builds the
    R9-R14 registries from the WHOLE repo regardless of ``paths``: a
    cross-file contract can't be checked against a diff-shaped slice
    of itself (the handler may be in the diff while the classification
    set is not)."""
    selected = set(rules) if rules else set(ALL_RULES)
    findings: List[Finding] = []
    if "R1" in selected:
        findings += check_lock_order(prog)
    if "R2" in selected:
        findings += check_blocking_under_lock(prog)
    if "R3" in selected:
        findings += check_aliased_state(prog)
    if "R4" in selected:
        findings += check_loop_affinity(prog)
    if "R5" in selected:
        findings += check_refcount_hygiene(prog)
    if "R6" in selected:
        # Orphan scan covers the WHOLE repo, not just the analyzed
        # paths: both shipped pyc-only packages lived under tools/ and
        # _private/debug/, which a ray_tpu/-scoped scan would miss.
        findings += check_pyc_orphans([repo_root], repo_root)
    if "R7" in selected:
        findings += check_silent_swallow(prog)
    if "R8" in selected:
        findings += check_bare_threading(prog)
    if selected & set(PROTOCOL_RULES):
        # Protocol registries are cross-file by nature: the scan set
        # widens to tests/ and tools/ on gate-shaped runs (see
        # protocol.protocol_scan_paths) so both sides of each contract
        # are in evidence, and it always stays global in
        # --changed-only mode.
        proto_paths = [os.path.join(repo_root, "ray_tpu")] \
            if global_protocol else paths
        proto = extract_protocol(proto_paths, repo_root)
        findings += run_protocol_rules(proto, selected)
        # `# graftcheck: ok RN <why>` on (or right above) the flagged
        # line suppresses that rule there — for code that exercises a
        # contract's failure mode on purpose (e.g. tests arming
        # synthetic fault points against the injector itself).
        findings = [f for f in findings
                    if not proto.suppressed(f.rule, f.path, f.line)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # Two identical defects in one function (e.g. two unfloored
    # decrements of the same attr) must not collapse to one
    # fingerprint — baselining one would silently grandfather both.
    # Suffix repeats with an occurrence index (line order is stable
    # within a function, so the suffix survives unrelated line shifts).
    seen: Dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint
        n = seen.get(fp, 0)
        seen[fp] = n + 1
        if n:
            f.detail = f"{f.detail or f.message}#{n + 1}"
    return findings
