"""graftcheck's whole-program model: parse the tree, resolve locks,
attribute types and call edges, and hand rule passes a queryable index.

Every rule in :mod:`graftcheck.rules` was paid for at runtime first
(ISSUE 7): the PR-6 store-lock -> refcount-lock ABBA deadlock (R1), the
GCS view aliasing a raylet's live ``NodeResources`` ledger (R3), the
duplicate terminal transition driving refcounts negative (R5).  The
analyzer is deliberately *project-shaped*: it understands this repo's
idioms (``self._lock = diag_rlock(...)``, ``loop.post(self.tick, ...)``,
``with self._lock:``) rather than aiming for soundness on arbitrary
Python.  Over-approximation is expected and absorbed by the committed
baseline (see :mod:`graftcheck.baseline`).

Resolution rules, in order of trust:

* lock attributes — ``self.X = threading.Lock()/RLock()/Condition()`` or
  the ``diag_*`` factories; ``Condition(self._lock)`` aliases the
  condition to the wrapped lock's node;
* attribute types — ``self.X = ClassName(...)`` against the global class
  registry, plus a snake_case->CamelCase naming heuristic for
  constructor parameters (``raylet`` -> ``Raylet``), which is how the
  cross-component edges (task manager -> store -> refcounter) resolve;
* call edges — ``self.m()``, ``self.attr.m()``, ``mod.f()``, ``f()``;
  anything dynamic (stored callbacks, ``reply()``) is out of scope by
  design.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

LOCK_FACTORIES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "diag_lock": "lock",
    "diag_rlock": "rlock",
    "diag_condition": "condition",
}

# Registrations that hand a closure to an EVENT LOOP thread (legitimate
# @loop_only call sites).  Deliberately excludes DaemonPool.submit —
# pool callbacks run on arbitrary pump threads, which is exactly the
# off-loop shape R4 exists to catch.
LOOP_POST_METHODS = {"post", "schedule_every", "schedule_after"}


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative
    line: int
    symbol: str        # enclosing qualname (Class.method / module scope)
    message: str
    detail: str = ""   # stable, line-number-free content for fingerprints

    @property
    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.path}|{self.symbol}|{self.detail or self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: "
                f"{self.message}  (fingerprint {self.fingerprint})")


@dataclass
class FunctionModel:
    qualname: str                  # "Class.method" or "function"
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    cls: Optional["ClassModel"]
    module: "ModuleModel"
    loop_only_kind: Optional[str] = None
    #: names of nested defs handed to loop.post/schedule_* in this body
    loop_entry_closures: Set[str] = field(default_factory=set)


@dataclass
class ClassModel:
    name: str
    module: "ModuleModel"
    node: ast.ClassDef
    methods: Dict[str, FunctionModel] = field(default_factory=dict)
    #: attr -> (lock_id, kind)
    lock_attrs: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: attr -> class name (for cross-component call resolution)
    attr_types: Dict[str, str] = field(default_factory=dict)
    #: attrs with "pending" in the name assigned anywhere in the class
    pending_attrs: Set[str] = field(default_factory=set)


@dataclass
class ModuleModel:
    path: str                      # repo-relative
    modname: str                   # dotted-ish short name
    tree: ast.Module
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    functions: Dict[str, FunctionModel] = field(default_factory=dict)
    #: module-global var -> (lock_id, kind)
    module_locks: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: local alias -> imported module short name ("time", "fault_injection")
    import_aliases: Dict[str, str] = field(default_factory=dict)


_SNAKE_RE = re.compile(r"_+")


def _camel(snake: str) -> str:
    return "".join(p.capitalize() for p in _SNAKE_RE.split(snake.strip("_"))
                   if p)


def _call_tail(func: ast.AST) -> Optional[str]:
    """Terminal name of a call target: threading.Lock -> 'Lock'."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class Program:
    """The analyzed tree: modules, a global class registry, lock ids and
    an interprocedural may-acquire cache."""

    def __init__(self):
        self.modules: List[ModuleModel] = []
        self.class_registry: Dict[str, ClassModel] = {}
        #: lock_id -> kind ("lock" | "rlock" | "condition")
        self.lock_kinds: Dict[str, str] = {}
        self._may_acquire_cache: Dict[int, Set[str]] = {}
        self._loop_only_by_name: Dict[str, List[FunctionModel]] = {}

    # -- construction ----------------------------------------------------
    def add_source(self, path: str, rel: str, source: str) -> None:
        tree = ast.parse(source, filename=path)
        modname = os.path.splitext(os.path.basename(rel))[0]
        mod = ModuleModel(path=rel, modname=modname, tree=tree)
        self._collect_imports(mod)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                cm = ClassModel(name=node.name, module=mod, node=node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fm = FunctionModel(
                            qualname=f"{node.name}.{item.name}",
                            node=item, cls=cm, module=mod)
                        fm.loop_only_kind = self._loop_only_kind(item)
                        cm.methods[item.name] = fm
                mod.classes[node.name] = cm
                # Last definition wins on name collisions across modules;
                # names in this tree are unique in practice.
                self.class_registry[node.name] = cm
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fm = FunctionModel(qualname=node.name, node=node, cls=None,
                                   module=mod)
                fm.loop_only_kind = self._loop_only_kind(node)
                mod.functions[node.name] = fm
            elif isinstance(node, ast.Assign):
                self._maybe_module_lock(mod, node)
        self.modules.append(mod)

    def finalize(self) -> None:
        """Second pass: lock attrs, attr types, condition aliasing, loop
        entry closures.  Needs the full class registry, hence separate
        from :meth:`add_source`."""
        for mod in self.modules:
            for cm in mod.classes.values():
                self._collect_class_state(cm)
        for mod in self.modules:
            for fm in self._functions(mod):
                self._collect_loop_entries(fm)
                if fm.loop_only_kind:
                    self._loop_only_by_name.setdefault(
                        fm.node.name, []).append(fm)

    def _functions(self, mod: ModuleModel) -> Iterable[FunctionModel]:
        yield from mod.functions.values()
        for cm in mod.classes.values():
            yield from cm.methods.values()

    def all_functions(self) -> Iterable[FunctionModel]:
        for mod in self.modules:
            yield from self._functions(mod)

    def _collect_imports(self, mod: ModuleModel) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    short = alias.name.split(".")[-1]
                    mod.import_aliases[alias.asname or short] = short
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    mod.import_aliases[alias.asname or alias.name] = \
                        alias.name

    def _loop_only_kind(self, fn: ast.AST) -> Optional[str]:
        for dec in getattr(fn, "decorator_list", []):
            if (isinstance(dec, ast.Call)
                    and _call_tail(dec.func) == "loop_only" and dec.args):
                arg = dec.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str):
                    return arg.value
                return "?"
        return None

    def _lock_factory_kind(self, call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        tail = _call_tail(call.func)
        if tail not in LOCK_FACTORIES:
            return None
        # `threading.Condition` / bare `Condition` / `diag_condition` all
        # count; anything else named Lock (e.g. a local class) is not a
        # pattern this tree uses.
        return LOCK_FACTORIES[tail]

    def _maybe_module_lock(self, mod: ModuleModel, node: ast.Assign) -> None:
        kind = self._lock_factory_kind(node.value)
        if kind is None:
            return
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                lock_id = f"{mod.modname}.{tgt.id}"
                mod.module_locks[tgt.id] = (lock_id, kind)
                self.lock_kinds[lock_id] = kind

    def _collect_class_state(self, cm: ClassModel) -> None:
        # Pass A: direct lock creations + attr types + pending attrs.
        cond_wraps: List[Tuple[str, ast.Call]] = []
        for fm in cm.methods.values():
            for node in ast.walk(fm.node):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                attr = _is_self_attr(node.targets[0])
                if attr is None:
                    continue
                if "pending" in attr:
                    cm.pending_attrs.add(attr)
                kind = self._lock_factory_kind(node.value)
                if kind is not None:
                    call = node.value
                    wraps_lock = (
                        kind == "condition" and call.args
                        and _is_self_attr(call.args[0]) is not None)
                    if wraps_lock:
                        cond_wraps.append((attr, call))
                    else:
                        lock_id = f"{cm.name}.{attr}"
                        cm.lock_attrs[attr] = (lock_id, kind)
                        self.lock_kinds[lock_id] = kind
                    continue
                if isinstance(node.value, ast.Call):
                    tail = _call_tail(node.value.func)
                    if tail in self.class_registry:
                        cm.attr_types[attr] = tail
                elif isinstance(node.value, ast.Name):
                    # self._raylet = raylet  (ctor param, by naming)
                    guess = _camel(node.value.id)
                    if guess in self.class_registry:
                        cm.attr_types[attr] = guess
        # Pass B: Condition(self._lock) aliases to the wrapped lock.
        for attr, call in cond_wraps:
            wrapped = _is_self_attr(call.args[0])
            if wrapped in cm.lock_attrs:
                cm.lock_attrs[attr] = cm.lock_attrs[wrapped]
            else:
                lock_id = f"{cm.name}.{attr}"
                cm.lock_attrs[attr] = (lock_id, "condition")
                self.lock_kinds[lock_id] = "condition"

    def _collect_loop_entries(self, fm: FunctionModel) -> None:
        """Nested functions handed to ``loop.post(fn, ...)`` (or
        ``schedule_*`` / pool ``submit``) run on the loop thread: calls
        they make to @loop_only methods are legitimate."""
        for node in ast.walk(fm.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if tail not in LOOP_POST_METHODS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    fm.loop_entry_closures.add(arg.id)

    # -- resolution ------------------------------------------------------
    def resolve_lock(self, fm: FunctionModel, expr: ast.AST) -> Optional[str]:
        """Lock id for a `with EXPR:` context item, or None."""
        attr = _is_self_attr(expr)
        if attr is not None and fm.cls is not None:
            hit = fm.cls.lock_attrs.get(attr)
            return hit[0] if hit else None
        if isinstance(expr, ast.Name):
            hit = fm.module.module_locks.get(expr.id)
            return hit[0] if hit else None
        # self.attr._lock — another component's lock taken directly.
        if (isinstance(expr, ast.Attribute)
                and (inner := _is_self_attr(expr.value)) is not None
                and fm.cls is not None):
            tcls = self.class_registry.get(
                fm.cls.attr_types.get(inner, ""))
            if tcls is not None:
                hit = tcls.lock_attrs.get(expr.attr)
                return hit[0] if hit else None
        return None

    def resolve_call(self, fm: FunctionModel,
                     call: ast.Call) -> Optional[FunctionModel]:
        func = call.func
        if isinstance(func, ast.Name):
            target = fm.module.functions.get(func.id)
            if target is not None:
                return target
            cls = fm.module.classes.get(func.id) or (
                self.class_registry.get(func.id)
                if func.id in fm.module.import_aliases else None)
            if cls is not None:
                return cls.methods.get("__init__")
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fm.cls is not None:
                return fm.cls.methods.get(func.attr)
            alias = fm.module.import_aliases.get(base.id)
            if alias is not None:
                for mod in self.modules:
                    if mod.modname == alias:
                        return mod.functions.get(func.attr)
            guess = _camel(base.id)
            tcls = self.class_registry.get(guess)
            if tcls is not None and base.id not in ("self",):
                return tcls.methods.get(func.attr)
            return None
        inner = _is_self_attr(base)
        if inner is not None and fm.cls is not None:
            tname = fm.cls.attr_types.get(inner)
            if tname is None:
                return None
            tcls = self.class_registry.get(tname)
            if tcls is not None:
                return tcls.methods.get(func.attr)
        return None

    # -- interprocedural may-acquire -------------------------------------
    def may_acquire(self, fm: FunctionModel,
                    _stack: Optional[Set[int]] = None) -> Set[str]:
        """Locks ``fm`` may take anywhere in itself or its (resolvable)
        callees.  Over-approximate by construction; recursion-safe."""
        key = id(fm.node)
        cached = self._may_acquire_cache.get(key)
        if cached is not None:
            return cached
        stack = _stack if _stack is not None else set()
        if key in stack:
            return set()
        stack.add(key)
        acquired: Set[str] = set()
        for node in ast.walk(fm.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self.resolve_lock(fm, item.context_expr)
                    if lid is not None:
                        acquired.add(lid)
            elif isinstance(node, ast.Call):
                callee = self.resolve_call(fm, node)
                if callee is not None:
                    acquired |= self.may_acquire(callee, stack)
        stack.discard(key)
        if _stack is None or not stack:
            self._may_acquire_cache[key] = acquired
        return acquired

    def loop_only_candidates(self, name: str) -> List[FunctionModel]:
        return self._loop_only_by_name.get(name, [])


def load_program(paths: List[str], repo_root: str) -> Tuple[Program, List[Finding]]:
    """Parse every .py under ``paths`` into one Program.  Unparseable
    files become findings rather than crashes."""
    prog = Program()
    errors: List[Finding] = []
    for path in sorted(_iter_py(paths)):
        rel = os.path.relpath(path, repo_root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            prog.add_source(path, rel, src)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(Finding(
                rule="parse", path=rel,
                line=getattr(e, "lineno", 0) or 0, symbol="<module>",
                message=f"unparseable: {e}", detail="unparseable"))
    prog.finalize()
    return prog, errors


def _iter_py(paths: List[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
