"""Protocol-model extraction for graftcheck v2 (rules R9-R14).

PRs 14-18 grew a distributed-protocol surface held together by
convention: mutating RPC verbs must be classified in ``rpc/verbs.py``
to get retry/dedup protection, node-stamped head-bound verbs must pass
the incarnation fence gate, fault points fire only when the ``arm()``
string matches a ``hook()`` site, config knobs work only when the
declared dataclass field and the read site agree on a name, metric
series silently corrupt when two writers disagree on the type, and
PR 17's striped locks depend on an at-most-one-stripe discipline.

This module walks the analyzed sources ONCE and builds the registries
those conventions live in — a protocol model — so the R9-R14 rule
passes in :mod:`graftcheck.rules` can cross-check both sides of each
contract.  Extraction is deliberately lighter than the analyzer's
``Program`` model: string-literal call arguments, dataclass field
tables, f-string lock names.  Non-literal registrations (e.g. the
chunked-transfer server's ``f"{prefix}_meta"`` verbs) are recorded as
*dynamic* and excluded from existence cross-checks rather than
guessed at.

Suppression: a source line (or the line above a finding) may carry
``# graftcheck: ok R11 <reason>`` to exempt that line from the named
rules — used by tests that exercise the fault injector with synthetic
point names.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# Attribute-call tails that register an RPC handler.
_RPC_REGISTER = {"register", "register_async"}

# Fault-injection call tails.  ``disarm`` is deliberately absent: a
# typo'd disarm always rides with a typo'd arm, and flagging both would
# double-report one defect.
_ARM_TAILS = {"arm"}
_ARM_WIRE_TAILS = {"arm_over_wire", "disarm_over_wire"}
_FIRED_TAILS = {"fired"}
_HOOK_TAILS = {"hook", "_hook"}

_METRIC_CTORS = {"Counter": "counter", "Gauge": "gauge",
                 "Histogram": "histogram"}
_METRIC_TYPES = {"counter", "gauge", "histogram"}

_VERB_SET_NAMES = ("IDEMPOTENT_VERBS", "DEDUP_VERBS", "CONTROL_VERBS",
                   "NO_RETRY_VERBS")

_PRAGMA_RE = re.compile(r"#\s*graftcheck:\s*ok\s+([R0-9, ]+)")

_STRIPE_OK_RE = re.compile(r"\[s\d{2}\]$")
#: a string is stripe-*like* (and therefore subject to the naming
#: contract) only when a "[s..." tail ends it — not merely anywhere a
#: "[s" appears (error messages, regexes).
_STRIPE_CAND_RE = re.compile(r"\[s(NN|\?|\d*)\]?$")


@dataclass
class Site:
    path: str          # repo-relative
    line: int
    symbol: str        # enclosing qualname


@dataclass
class Handler:
    verb: str
    site: Site                       # the register(...) call
    func: Optional[ast.AST] = None   # resolved handler FunctionDef
    cls: Optional[ast.ClassDef] = None   # class owning the handler


@dataclass
class StripeFamily:
    base: str                            # e.g. "ReferenceCounter._lock"
    decl_sites: List[Site] = field(default_factory=list)
    #: class names whose construction creates a stripe of this family
    #: (the f-string lock name is passed to the stripe class's __init__)
    stripe_classes: Set[str] = field(default_factory=set)
    #: True when the diag_* factory is called directly with the
    #: stripe-patterned name (no wrapper class)
    direct: bool = False


@dataclass
class ProtocolModel:
    #: verb -> handler registrations (server side)
    server_verbs: Dict[str, List[Handler]] = field(default_factory=dict)
    #: True when at least one registration used a non-literal verb name
    #: (dynamic verbs exist; existence cross-checks must stay lenient)
    dynamic_server_verbs: bool = False
    #: verb -> client call/call_async sites
    client_verbs: Dict[str, List[Site]] = field(default_factory=dict)
    #: verb -> client sites whose payload passed through stamp()
    stamped_verbs: Dict[str, List[Site]] = field(default_factory=dict)
    #: verb -> _fence_gate(payload, "verb") sites
    gated_verbs: Dict[str, List[Site]] = field(default_factory=dict)
    #: IDEMPOTENT_VERBS / DEDUP_VERBS / CONTROL_VERBS / NO_RETRY_VERBS
    verb_sets: Dict[str, Set[str]] = field(default_factory=dict)
    verb_set_sites: Dict[str, Site] = field(default_factory=dict)

    #: fault point -> hook()/fire sites
    hook_points: Dict[str, List[Site]] = field(default_factory=dict)
    #: fault point -> arm()/arm_over_wire()/env-literal/fired() sites
    armed_points: Dict[str, List[Site]] = field(default_factory=dict)

    #: Config dataclass field -> declaration site
    config_fields: Dict[str, Site] = field(default_factory=dict)
    #: attr name -> read sites on a get_config()-resolved receiver
    config_reads: Dict[str, List[Site]] = field(default_factory=dict)
    #: methods/classvars of the Config class (reads of these are API
    #: use, not knob reads)
    config_methods: Set[str] = field(default_factory=set)
    #: attr names read on receivers merely NAMED like a config
    #: (``cfg.x`` where cfg is a parameter, or behind a ``_config()``
    #: wrapper) plus ``getattr(cfg, "x", d)`` literals.  Too weak to
    #: prove a read names a real field (model configs are also called
    #: ``cfg``), so these only count toward the "declared but never
    #: read" direction, never the "read but undeclared" one.
    config_reads_loose: Set[str] = field(default_factory=set)
    #: "RAY_TPU_<FIELD>" env literals seen anywhere (a field consumed
    #: straight off the env still counts as read)
    env_literals: Set[str] = field(default_factory=set)

    #: metric name -> [(site, declared type)]
    metric_writes: Dict[str, List[Tuple[Site, str]]] = \
        field(default_factory=dict)
    #: metric name -> get_value(...) read sites
    metric_reads: Dict[str, List[Site]] = field(default_factory=dict)

    #: stripe family base -> StripeFamily
    stripe_families: Dict[str, StripeFamily] = field(default_factory=dict)
    #: malformed stripe-like lock names: (site, offending name text)
    stripe_name_violations: List[Tuple[Site, str]] = \
        field(default_factory=list)

    #: (relpath, line) -> rules suppressed on that line
    pragmas: Dict[Tuple[str, int], Set[str]] = field(default_factory=dict)

    #: parsed modules for rule passes that need a structural walk (R14)
    trees: List[Tuple[str, ast.Module]] = field(default_factory=list)

    def suppressed(self, rule: str, path: str, line: int) -> bool:
        for ln in (line, line - 1):
            if rule in self.pragmas.get((path, ln), ()):
                return True
        return False


# ---------------------------------------------------------------------------
# File collection.


def _iter_py_files(paths: List[str], repo_root: str) -> List[str]:
    out: List[str] = []
    fixtures = os.path.join("tools", "graftcheck", "fixtures")
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            rel = os.path.relpath(dirpath, repo_root)
            if rel.startswith(fixtures) or "__pycache__" in dirpath:
                dirnames[:] = []
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    # De-dup while preserving order (a file may be reachable twice).
    seen: Set[str] = set()
    uniq = []
    for f in out:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            uniq.append(f)
    return uniq


def protocol_scan_paths(paths: List[str], repo_root: str) -> List[str]:
    """The registry scan set for an analysis of ``paths``.

    When the analyzed set covers the repo's ``ray_tpu`` tree (the
    tier-1 gate shape), the protocol scan additionally walks ``tests/``
    and ``tools/`` — arm sites, knob reads and metric asserts living in
    tests are evidence a contract side exists (R6 whole-repo-scan
    precedent).  A single-file analysis (fixture tests, editor runs)
    scans only that file, keeping fixtures self-contained.
    """
    roots = {os.path.abspath(p) for p in paths}
    gate_shaped = os.path.abspath(os.path.join(repo_root, "ray_tpu")) \
        in roots or os.path.abspath(repo_root) in roots
    if not gate_shaped:
        return list(paths)
    extra = []
    for sub in ("tests", "tools"):
        d = os.path.join(repo_root, sub)
        if os.path.isdir(d) and os.path.abspath(d) not in roots:
            extra.append(d)
    return list(paths) + extra


# ---------------------------------------------------------------------------
# Small AST helpers (kept local: the protocol pass must not depend on
# the heavy Program model).


def _tail(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _lit(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fmt_stripe_name(node: ast.AST) -> Optional[str]:
    """Render a (possibly f-string) lock-name argument to a checkable
    text, with ``{...:02d}`` placeholders collapsed to ``NN`` and any
    other placeholder to ``?``.  Returns None for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                out.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                spec = ""
                if isinstance(v.format_spec, ast.JoinedStr):
                    spec = "".join(
                        str(c.value) for c in v.format_spec.values
                        if isinstance(c, ast.Constant))
                out.append("NN" if spec == "02d" else "?")
        return "".join(out)
    return None


def _parse_fault_env(value: str) -> List[str]:
    """Point names out of a ``RAY_TPU_FAULT_POINTS`` spec string:
    ``"spill.write:error:2,rpc.send@verb=heartbeat:drop:-1"``."""
    points = []
    for part in value.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        head = part.split(":", 1)[0]
        head = head.split("@", 1)[0].strip()
        if head:
            points.append(head)
    return points


class _Scope:
    """Tracks the class/function nesting for qualnames and per-function
    local bindings (config receivers, stamped payload names)."""

    def __init__(self):
        self.stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self.stack) or "<module>"


# ---------------------------------------------------------------------------
# Extraction visitor.


class _Extractor(ast.NodeVisitor):
    def __init__(self, model: ProtocolModel, relpath: str):
        self.m = model
        self.rel = relpath
        self.scope = _Scope()
        self.cls_stack: List[ast.ClassDef] = []
        # handler-resolution tables, filled on first pass per module
        self.methods: Dict[Tuple[str, str], Tuple[ast.AST, ast.ClassDef]] = {}
        self.functions: Dict[str, ast.AST] = {}
        # per-function state
        self._cfg_names: List[Set[str]] = []
        self._stamped_names: List[Set[str]] = []
        self._reg_names: List[Set[str]] = []

    # -- scope plumbing --------------------------------------------------

    def _site(self, node: ast.AST) -> Site:
        return Site(self.rel, getattr(node, "lineno", 0),
                    self.scope.qualname)

    def visit_ClassDef(self, node: ast.ClassDef):
        self.scope.stack.append(node.name)
        self.cls_stack.append(node)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[(node.name, item.name)] = (item, node)
        if node.name == "Config" and any(
                _tail(d) == "dataclass" or
                (isinstance(d, ast.Call) and _tail(d.func) == "dataclass")
                for d in node.decorator_list):
            self._collect_config_fields(node)
        self.generic_visit(node)
        self.cls_stack.pop()
        self.scope.stack.pop()

    def _visit_func(self, node):
        if not self.cls_stack and not self.scope.stack:
            self.functions[node.name] = node
        self.scope.stack.append(node.name)
        self._cfg_names.append(set())
        self._stamped_names.append(set())
        self._reg_names.append(set())
        self.generic_visit(node)
        self._reg_names.pop()
        self._stamped_names.pop()
        self._cfg_names.pop()
        self.scope.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- declarations ----------------------------------------------------

    def _collect_config_fields(self, node: ast.ClassDef):
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and \
                    isinstance(item.target, ast.Name):
                name = item.target.id
                if not name.startswith("_"):
                    self.m.config_fields.setdefault(
                        name, Site(self.rel, item.lineno, "Config"))
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.m.config_methods.add(item.name)
            elif isinstance(item, ast.Assign):
                for t in item.targets:
                    if isinstance(t, ast.Name):
                        self.m.config_methods.add(t.id)

    def visit_Assign(self, node: ast.Assign):
        # IDEMPOTENT_VERBS = frozenset({...}) — the classification sets.
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id in _VERB_SET_NAMES and \
                isinstance(node.value, ast.Call) and \
                _tail(node.value.func) == "frozenset":
            names: Set[str] = set()
            for sub in ast.walk(node.value):
                s = _lit(sub)
                if s is not None:
                    names.add(s)
            key = node.targets[0].id
            self.m.verb_sets.setdefault(key, set()).update(names)
            self.m.verb_set_sites.setdefault(key, self._site(node))
        self._track_bindings(node.targets, node.value)
        # env assignment form: os.environ["RAY_TPU_FAULT_POINTS"] = "..."
        self._scan_env_literals(node)
        self.generic_visit(node)

    def _track_bindings(self, targets, value):
        """Record names bound to get_config() / *.stamp(...) /
        get_metrics_registry() within the current function."""
        if not self._cfg_names or not isinstance(value, ast.Call):
            return
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            return
        tail = _tail(value.func)
        if tail == "get_config":
            self._cfg_names[-1].update(names)
        elif tail == "stamp":
            self._stamped_names[-1].update(names)
        elif tail == "get_metrics_registry":
            self._reg_names[-1].update(names)

    # -- reads & calls ---------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load):
            base = node.value
            is_cfg = (isinstance(base, ast.Call) and
                      _tail(base.func) == "get_config")
            if not is_cfg and isinstance(base, ast.Name) and \
                    self._cfg_names and base.id in self._cfg_names[-1]:
                is_cfg = True
            if is_cfg and not node.attr.startswith("__"):
                self.m.config_reads.setdefault(node.attr, []).append(
                    self._site(node))
            elif isinstance(base, ast.Name) and \
                    base.id in ("cfg", "_cfg", "config", "conf"):
                self.m.config_reads_loose.add(node.attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        tail = _tail(node.func)
        args = node.args

        # --- RPC server registrations ---
        if isinstance(node.func, ast.Attribute) and tail in _RPC_REGISTER:
            verb = _lit(args[0]) if args else None
            handler = args[1] if len(args) > 1 else None
            is_metric_decl = (len(args) > 1 and _lit(args[1])
                              in _METRIC_TYPES)
            if is_metric_decl:
                # MetricsRegistry.register(name, mtype, ...)
                name = _lit(args[0])
                if name is not None:
                    self.m.metric_writes.setdefault(name, []).append(
                        (self._site(node), _lit(args[1])))
            elif verb is not None and handler is not None:
                h = Handler(verb, self._site(node))
                h.func, h.cls = self._resolve_handler(handler)
                self.m.server_verbs.setdefault(verb, []).append(h)
            elif handler is not None and verb is None and args:
                # f-string verb (chunked-transfer prefix verbs)
                self.m.dynamic_server_verbs = True

        # --- RPC client call sites ---
        if isinstance(node.func, ast.Attribute) and \
                tail in ("call", "call_async"):
            verb = _lit(args[0]) if args else None
            if verb is not None:
                site = self._site(node)
                self.m.client_verbs.setdefault(verb, []).append(site)
                payload = args[1] if len(args) > 1 else None
                if payload is not None and self._is_stamped(payload):
                    self.m.stamped_verbs.setdefault(verb, []).append(site)

        # --- fence gate ---
        if tail == "_fence_gate" and len(args) >= 2:
            verb = _lit(args[1])
            if verb is not None:
                self.m.gated_verbs.setdefault(verb, []).append(
                    self._site(node))

        # --- fault points ---
        if tail in _HOOK_TAILS:
            point = _lit(args[0]) if args else None
            if point is None:
                for kw in node.keywords:
                    if kw.arg == "point":
                        point = _lit(kw.value)
            if point is not None:
                self.m.hook_points.setdefault(point, []).append(
                    self._site(node))
        if tail in _ARM_TAILS or tail in _FIRED_TAILS:
            point = _lit(args[0]) if args else None
            if point is not None:
                self.m.armed_points.setdefault(point, []).append(
                    self._site(node))
        if tail in _ARM_WIRE_TAILS and len(args) >= 2:
            point = _lit(args[1])
            if point is not None:
                self.m.armed_points.setdefault(point, []).append(
                    self._site(node))

        # --- metric writes/reads ---
        if tail == "record_internal" and args:
            name = _lit(args[0])
            if name is not None:
                mtype = "gauge"
                if len(args) > 2 and _lit(args[2]) in _METRIC_TYPES:
                    mtype = _lit(args[2])
                for kw in node.keywords:
                    if kw.arg == "mtype" and _lit(kw.value) in _METRIC_TYPES:
                        mtype = _lit(kw.value)
                self.m.metric_writes.setdefault(name, []).append(
                    (self._site(node), mtype))
        elif tail == "observe_internal" and args:
            name = _lit(args[0])
            if name is not None:
                self.m.metric_writes.setdefault(name, []).append(
                    (self._site(node), "histogram"))
        elif tail in _METRIC_CTORS and isinstance(node.func, ast.Name) \
                and args:
            name = _lit(args[0])
            if name is not None:
                self.m.metric_writes.setdefault(name, []).append(
                    (self._site(node), _METRIC_CTORS[tail]))
        elif tail == "get_value" and args:
            name = _lit(args[0])
            if name is not None:
                self.m.metric_reads.setdefault(name, []).append(
                    self._site(node))
        elif tail in ("inc", "observe") and args and \
                isinstance(node.func, ast.Attribute):
            # Direct registry writes (KeyError at runtime when the name
            # was never registered) — only when the receiver resolves
            # to the metrics registry; ``inc`` is too generic otherwise.
            recv = node.func.value
            is_reg = (isinstance(recv, ast.Call) and
                      _tail(recv.func) == "get_metrics_registry")
            if not is_reg and isinstance(recv, ast.Name) and \
                    self._reg_names and recv.id in self._reg_names[-1]:
                is_reg = True
            name = _lit(args[0])
            if is_reg and name is not None:
                mtype = "counter" if tail == "inc" else "histogram"
                self.m.metric_writes.setdefault(name, []).append(
                    (self._site(node), mtype))

        # getattr(cfg, "knob", default) — a knob read by literal name.
        if tail == "getattr" and isinstance(node.func, ast.Name) and \
                len(args) >= 2:
            name = _lit(args[1])
            if name is not None:
                self.m.config_reads_loose.add(name)

        # --- RAY_TPU_FAULT_POINTS env literals & RAY_TPU_* env reads ---
        self._scan_env_literals(node)

        # --- stripe lock names ---
        self._scan_stripe_name(node, tail)

        self.generic_visit(node)

    # -- helpers ---------------------------------------------------------

    def _is_stamped(self, payload: ast.AST) -> bool:
        if isinstance(payload, ast.Call) and _tail(payload.func) == "stamp":
            return True
        if isinstance(payload, ast.Name) and self._stamped_names and \
                payload.id in self._stamped_names[-1]:
            return True
        return False

    def _resolve_handler(self, expr: ast.AST):
        """``self._handle_x`` / bare name -> (FunctionDef, ClassDef)."""
        name = None
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        if name is None:
            return None, None
        for cls in reversed(self.cls_stack):
            hit = self.methods.get((cls.name, name))
            if hit:
                return hit
        # Registration may live outside the owning class (a host object
        # registering its raylet's methods) — fall back to any class in
        # this module defining the method, then module functions.
        for (_cls, meth), hit in self.methods.items():
            if meth == name:
                return hit
        fn = self.functions.get(name)
        return (fn, None) if fn is not None else (None, None)

    def _scan_env_literals(self, node: ast.AST):
        strs = [s for s in (_lit(a) for a in ast.walk(node))
                if s is not None]
        if any(s == "RAY_TPU_FAULT_POINTS" for s in strs):
            for s in strs:
                if s == "RAY_TPU_FAULT_POINTS":
                    continue
                for point in _parse_fault_env(s):
                    self.m.armed_points.setdefault(point, []).append(
                        self._site(node))
        for s in strs:
            if s.startswith("RAY_TPU_") and s.isupper():
                self.m.env_literals.add(s)

    def _scan_stripe_name(self, node: ast.Call, tail: Optional[str]):
        """A diag_* factory or a class constructor taking a
        ``Base._lock[sNN]``-patterned name argument declares a stripe
        of family ``Base._lock``.

        Scope: diag_* factory string args, plus f-string args to any
        call (stripe wrapper classes take the formatted name, e.g.
        ``_EventStripe(f"TaskEventBuffer._lock[s{i:02d}]")``).  Plain
        constants passed to arbitrary calls are NOT stripe names
        (regexes, prefix matches, error messages)."""
        is_diag = tail in ("diag_lock", "diag_rlock", "diag_condition")
        for arg in node.args:
            if not is_diag and not isinstance(arg, ast.JoinedStr):
                continue
            text = _fmt_stripe_name(arg)
            if text is None or "[s" not in text or \
                    not _STRIPE_CAND_RE.search(text):
                continue
            site = self._site(node)
            if not _STRIPE_OK_RE.search(text.replace("NN", "00")):
                self.m.stripe_name_violations.append((site, text))
                continue
            base = text[:text.rindex("[s")]
            fam = self.m.stripe_families.setdefault(
                base, StripeFamily(base))
            fam.decl_sites.append(site)
            is_diag = tail in ("diag_lock", "diag_rlock", "diag_condition")
            if is_diag:
                fam.direct = True
            elif isinstance(node.func, ast.Name):
                fam.stripe_classes.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                fam.stripe_classes.add(node.func.attr)


# ---------------------------------------------------------------------------
# Entry point.


def extract_protocol(paths: List[str], repo_root: str) -> ProtocolModel:
    model = ProtocolModel()
    for fpath in _iter_py_files(protocol_scan_paths(paths, repo_root),
                                repo_root):
        try:
            with open(fpath, "r", encoding="utf-8", errors="replace") as f:
                src = f.read()
            tree = ast.parse(src, filename=fpath)
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(fpath, repo_root)
        for i, text in enumerate(src.splitlines(), start=1):
            mt = _PRAGMA_RE.search(text)
            if mt:
                rules = {r.strip() for r in
                         re.split(r"[,\s]+", mt.group(1)) if r.strip()}
                model.pragmas.setdefault((rel, i), set()).update(rules)
        ex = _Extractor(model, rel)
        # Pre-pass: method tables must exist before handler resolution,
        # and registrations can precede handler defs in source order.
        for sub in ast.walk(tree):
            if isinstance(sub, ast.ClassDef):
                for item in sub.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        ex.methods[(sub.name, item.name)] = (item, sub)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ex.functions.setdefault(sub.name, sub)
        ex.visit(tree)
        model.trees.append((rel, tree))
    return model
