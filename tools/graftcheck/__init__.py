"""graftcheck — project-specific concurrency-invariant static analysis.

Rules R1-R7 (see :mod:`graftcheck.rules`) encode the invariants this
repo has repeatedly paid for at runtime; the dynamic counterpart is the
lock-order witness in ``ray_tpu/_private/debug``.  Run as
``python -m graftcheck`` from the repo root; findings ratchet against
``baseline.json`` (:mod:`graftcheck.baseline`).
"""

from graftcheck.analyzer import Finding, Program, load_program  # noqa: F401
from graftcheck.rules import ALL_RULES, RULE_TITLES, run_all  # noqa: F401

__version__ = "1.0"
