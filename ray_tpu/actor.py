"""Actor API: ActorClass / ActorHandle / ActorMethod.

Parity: reference ``python/ray/actor.py`` — ``@remote`` on a class yields an
``ActorClass``; ``.remote(...)`` registers+schedules the actor via the GCS
(actor path §3.3 of SURVEY.md); ``ActorHandle.method.remote()`` submits
ordered actor tasks directly to the actor's worker; handles are serializable
and named actors are looked up via the GCS.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private import worker_context
from ray_tpu._private.executor import pack_args
from ray_tpu._private.ids import ActorID
from ray_tpu._private.task_spec import TaskType, make_spec
from ray_tpu.remote_function import (
    _normalized_env, _resource_dict, resolve_pg_strategy)

_DEFAULT_ACTOR_OPTIONS = dict(
    num_cpus=None, num_tpus=0, num_gpus=0, memory=0, resources=None,
    max_restarts=0, max_task_retries=0, max_concurrency=1,
    concurrency_groups=None,
    name=None, namespace=None, lifetime=None, scheduling_strategy=None,
    runtime_env=None,
)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs,
            num_returns=self._num_returns,
            concurrency_group=self._concurrency_group)

    def options(self, num_returns: int = 1,
                concurrency_group: str = "", **_):
        return ActorMethod(self._handle, self._method_name,
                           num_returns=num_returns,
                           concurrency_group=concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use .remote().")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name

    @classmethod
    def _from_gcs_actor(cls, gcs_actor):
        return cls(gcs_actor.actor_id,
                   class_name=gcs_actor.info().get("class_name", ""))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _submit_method(self, method_name: str, args, kwargs,
                       num_returns: int = 1,
                       concurrency_group: str = ""):
        w = worker_mod.global_worker()
        core = w.core_worker
        gcs_actor = w.cluster.gcs.actor_manager.get_actor(self._actor_id)
        creation = gcs_actor.creation_spec if gcs_actor else None
        flat = pack_args(args, kwargs)
        task_args, _, holders, borrowed = core.build_args(flat)
        parent = worker_context.current_task_spec()
        spec = make_spec(
            job_id=w.job_id,
            owner_id=core.worker_id,
            function_id=creation.function_id if creation else None,
            function_name=f"{self._class_name}.{method_name}",
            args=task_args,
            num_returns=num_returns,
            resources={},   # actor methods use the actor's held resources
            scheduling_strategy=None,
            parent_task_id=parent.task_id if parent else core.driver_task_id,
            task_type=TaskType.ACTOR_TASK,
            actor_id=self._actor_id,
            actor_method_name=method_name,
            concurrency_group=concurrency_group,
            max_retries=(creation.max_task_retries if creation else 0),
            borrowed_ids=borrowed,
        )
        refs = core.submit_actor_task(spec, holders=holders)
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"


def _rebuild_handle(actor_id, class_name):
    return ActorHandle(actor_id, class_name)


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._class_name = cls.__name__
        self._options = dict(_DEFAULT_ACTOR_OPTIONS)
        self._options.update(options or {})
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *a, **k):
        raise TypeError(f"Actors must be created with "
                        f"{self._class_name}.remote()")

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        o = self._options
        w = worker_mod.global_worker()
        if not w.connected:
            # Main-thread-only auto-init (see RemoteFunction._remote).
            import threading
            if threading.current_thread() is not threading.main_thread():
                raise RuntimeError(
                    "ray_tpu.init() has not been called yet (or the "
                    "cluster was shut down).")
            worker_mod.init()
        core = w.core_worker
        function_id = core.function_manager.export(self._cls)
        explicit = _resource_dict(o)
        # Reference semantics: default actors need 1 CPU to be *placed* but
        # hold 0 while alive; explicitly-requested resources (including an
        # explicit num_cpus=0) are held for the actor's lifetime (actor.py
        # _process_option_dict + task_spec.h GetRequiredPlacementResources).
        explicit_any = (o.get("num_cpus") is not None or o.get("num_tpus")
                        or o.get("num_gpus") or o.get("memory")
                        or o.get("resources"))
        resources = explicit if explicit_any else {"CPU": 1.0}
        resources, strategy, pg_id, bundle_idx = resolve_pg_strategy(
            o, resources)
        lifetime_resources = resources if explicit_any else {}
        flat = pack_args(args, kwargs)
        task_args, _, holders, borrowed = core.build_args(flat)
        actor_id = ActorID.from_random()
        parent = worker_context.current_task_spec()
        spec = make_spec(
            job_id=w.job_id,
            owner_id=core.worker_id,
            function_id=function_id,
            function_name=f"{self._class_name}.__init__",
            args=task_args,
            num_returns=0,
            resources=resources,
            scheduling_strategy=strategy,
            parent_task_id=parent.task_id if parent else core.driver_task_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            actor_id=actor_id,
            actor_creation=True,
            max_restarts=o.get("max_restarts", 0),
            max_task_retries=o.get("max_task_retries", 0),
            max_concurrency=o.get("max_concurrency", 1),
            concurrency_groups=o.get("concurrency_groups"),
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle_idx,
            runtime_env=_normalized_env(o.get("runtime_env"), w),
            lifetime_resources=lifetime_resources,
            borrowed_ids=borrowed,
        )
        namespace = o.get("namespace")
        core.create_actor(
            spec,
            name=o.get("name") or "",
            namespace=namespace if namespace is not None else w.namespace,
            detached=(o.get("lifetime") == "detached"),
        )
        return ActorHandle(actor_id, class_name=self._class_name)


def make_actor_class(cls, options) -> ActorClass:
    return ActorClass(cls, options)
