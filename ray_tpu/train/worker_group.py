"""WorkerGroup: a gang of actors that execute functions in lockstep.

Parity: reference ``python/ray/train/worker_group.py`` — ``WorkerGroup``
creates ``num_workers`` actors (optionally inside a placement group for
gang scheduling) and offers ``execute``/``execute_async`` (all workers)
and ``execute_single`` (one worker).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import placement_group, \
    remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


class _ExecutableActor:
    """Generic actor that runs arbitrary callables (BaseWorkerMixin)."""

    def __init__(self):
        self._state: Dict[str, Any] = {}

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)


class WorkerGroup:
    def __init__(self, num_workers: int = 1,
                 num_cpus_per_worker: float = 1,
                 num_tpus_per_worker: float = 0,
                 additional_resources_per_worker: Optional[Dict] = None,
                 use_placement_group: bool = True):
        if num_workers <= 0:
            raise ValueError("num_workers must be positive")
        self.num_workers = num_workers
        resources = dict(additional_resources_per_worker or {})
        self._pg = None
        options: Dict[str, Any] = dict(
            num_cpus=num_cpus_per_worker, resources=resources or None)
        if num_tpus_per_worker:
            options["num_tpus"] = num_tpus_per_worker
        if use_placement_group:
            bundle = {"CPU": num_cpus_per_worker}
            if num_tpus_per_worker:
                bundle["TPU"] = num_tpus_per_worker
            bundle.update(resources)
            self._pg = placement_group([dict(bundle)] * num_workers,
                                       strategy="PACK")
            ray_tpu.get(self._pg.ready())
            options["scheduling_strategy"] = \
                PlacementGroupSchedulingStrategy(self._pg)
        cls = ray_tpu.remote(**{k: v for k, v in options.items()
                                if v is not None})(_ExecutableActor)
        self.workers = []
        for i in range(num_workers):
            if self._pg is not None:
                cls_i = cls.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        self._pg, placement_group_bundle_index=i))
                self.workers.append(cls_i.remote())
            else:
                self.workers.append(cls.remote())

    def __len__(self):
        return len(self.workers)

    def execute_async(self, fn: Callable, *args, **kwargs) -> List:
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_single_async(self, rank: int, fn: Callable, *args, **kwargs):
        return self.workers[rank].execute.remote(fn, *args, **kwargs)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.execute_single_async(rank, fn, *args,
                                                     **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
