"""Backends + BackendExecutor: how a worker gang becomes a process group.

Parity: reference ``python/ray/train/backend.py`` (``BackendExecutor``
orchestrating start/setup/run over a ``WorkerGroup``) and
``python/ray/train/torch.py`` / ``tensorflow.py`` / ``horovod.py``
(backend configs that wire the framework's process group).

TPU-first: ``JaxConfig`` is the flagship backend — it creates a
collective group over the workers (gradient allreduce plane; XLA
collectives inside pjit/shard_map need no setup) and records each
worker's mesh coordinates. ``TorchConfig`` initializes a CPU gloo
process group when torch.distributed is available.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.session import Session, TrainingResult
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    """Base backend config; subclasses pick the Backend implementation."""

    def backend_name(self) -> str:
        return "base"

    def on_start(self, worker_group: WorkerGroup):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


@dataclass
class JaxConfig(BackendConfig):
    """Sets up the host-collective plane for data-parallel jax training.

    Inside each worker, ``ray_tpu.util.collective`` ops (allreduce of
    gradients) are available under ``group_name``; device-level
    collectives (psum over an ICI mesh) are expressed inside the user's
    pjit/shard_map program and need no process-group setup.
    """

    group_name: str = "train"

    def backend_name(self) -> str:
        return "jax"

    def on_start(self, worker_group: WorkerGroup):
        from ray_tpu.util.collective import collective
        n = len(worker_group)
        name = self.group_name

        def setup(rank):
            collective.init_collective_group(n, rank, group_name=name)
            base = name.split("~", 1)[0]
            if base != name:
                # User train functions address the group by the stable
                # documented name; resolve it per worker to this run's
                # scoped group so concurrent trainers don't collide.
                collective.set_group_alias(base, name)
            return True
        import ray_tpu
        ray_tpu.get([
            worker_group.execute_single_async(i, setup, i)
            for i in range(n)])

    def on_shutdown(self, worker_group: WorkerGroup):
        from ray_tpu.util.collective import collective
        name = self.group_name

        def teardown():
            try:
                collective.destroy_collective_group(name)
            except Exception:
                pass
        try:
            worker_group.execute(teardown)
        except Exception:
            pass


@dataclass
class TorchConfig(BackendConfig):
    """torch.distributed parity backend (reference ``train/torch.py``
    ``setup_torch_process_group``: MASTER_ADDR/PORT + init_process_group
    over TCP).

    When the workers are real OS processes (``worker_process_mode=
    process``) this initializes an actual gloo process group across
    them — ``torch.distributed.all_reduce`` et al. work natively inside
    the train function, DDP included.  When workers are in-process
    threads (the fast default) one shared torch runtime cannot host
    multiple ranks, so gradient averaging routes through the host
    collective plane like the jax backend.
    """

    backend: str = "gloo"
    init_method: str = "tcp"
    group_name: str = "train"
    timeout_s: float = 60.0

    def backend_name(self) -> str:
        return "torch"

    def on_start(self, worker_group: WorkerGroup):
        import os
        import ray_tpu
        n = len(worker_group)
        pids = worker_group.execute(os.getpid)
        if len(set(pids)) == n and os.getpid() not in pids:
            self._real_pg = True
            self._setup_process_group(worker_group, n)
            return
        self._real_pg = False
        from ray_tpu.util.collective import collective
        name = self.group_name

        def setup(rank):
            collective.init_collective_group(n, rank, group_name=name)
            base = name.split("~", 1)[0]
            if base != name:
                collective.set_group_alias(base, name)
            return True
        ray_tpu.get([
            worker_group.execute_single_async(i, setup, i)
            for i in range(n)])

    def _setup_process_group(self, worker_group: WorkerGroup, n: int):
        import ray_tpu

        def master_endpoint():
            import socket
            # Rank 0's host serves the TCP rendezvous; port 0 picked
            # here so the chosen port is free on THAT machine.
            s = socket.socket()
            s.bind(("0.0.0.0", 0))
            port = s.getsockname()[1]
            s.close()
            host = socket.gethostbyname(socket.gethostname())
            return host, port

        host, port = worker_group.execute_single(0, master_endpoint)
        backend, timeout_s = self.backend, self.timeout_s

        def setup(rank):
            import datetime
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
            dist.init_process_group(
                backend, init_method=f"tcp://{host}:{port}",
                rank=rank, world_size=n,
                timeout=datetime.timedelta(seconds=timeout_s))
            return True

        ray_tpu.get([
            worker_group.execute_single_async(i, setup, i)
            for i in range(n)])

    def on_shutdown(self, worker_group: WorkerGroup):
        if getattr(self, "_real_pg", False):
            def teardown():
                import torch.distributed as dist
                if dist.is_initialized():
                    dist.destroy_process_group()
                return True
            try:
                worker_group.execute(teardown)
            except Exception:
                pass
            return
        from ray_tpu.util.collective import collective
        name = self.group_name

        def teardown():
            try:
                collective.destroy_collective_group(name)
            except Exception:
                pass
        try:
            worker_group.execute(teardown)
        except Exception:
            pass


def _start_session_on_worker(run_id: str, fn: Callable, config: Dict,
                             rank: int, world_size: int,
                             checkpoint: Optional[Dict]):
    """Runs inside the worker actor: create + start the session."""
    import functools
    fn_bound = functools.partial(fn, dict(config)) if _fn_takes_config(fn) \
        else fn
    session = Session(fn_bound, world_rank=rank, local_rank=rank,
                      world_size=world_size, checkpoint=checkpoint)
    _WORKER_SESSIONS[(run_id, rank)] = session
    session.start()
    return True


def _fn_takes_config(fn: Callable) -> bool:
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


# In-process actors share module globals; key by (run_id, rank) so two
# concurrent BackendExecutors (e.g. parallel tune trials over
# to_tune_trainable) never cross-wire each other's sessions (see verify
# skill gotcha: module-level state is shared across "workers").
_WORKER_SESSIONS: Dict[Any, Session] = {}


def _get_next_on_worker(run_id: str, rank: int,
                        timeout: float = 300.0) -> TrainingResult:
    session = _WORKER_SESSIONS.get((run_id, rank))
    if session is None:
        return TrainingResult("error",
                              RuntimeError(f"no session for rank {rank}"))
    return session.get_next(timeout=timeout)


def _drop_session_on_worker(run_id: str, rank: int) -> bool:
    return _WORKER_SESSIONS.pop((run_id, rank), None) is not None


class TrainBackendError(RuntimeError):
    pass


class BackendExecutor:
    """Drives the worker gang through a training run (reference
    backend.py BackendExecutor.start/start_training/get_next_results)."""

    def __init__(self, backend_config: BackendConfig,
                 num_workers: int = 1,
                 num_cpus_per_worker: float = 1,
                 num_tpus_per_worker: float = 0,
                 additional_resources_per_worker: Optional[Dict] = None):
        import uuid
        self._config = backend_config
        self._num_workers = num_workers
        self._run_id = uuid.uuid4().hex[:12]
        self._worker_args = dict(
            num_workers=num_workers,
            num_cpus_per_worker=num_cpus_per_worker,
            num_tpus_per_worker=num_tpus_per_worker,
            additional_resources_per_worker=additional_resources_per_worker)
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        import copy
        import uuid
        # Fresh per run: executors are pickled into tune trainables, so
        # ids minted at __init__ would be shared by every unpickled copy.
        self._run_id = uuid.uuid4().hex[:12]
        self.worker_group = WorkerGroup(**self._worker_args)
        # Run a copy of the config with a run-scoped collective group so
        # concurrent executors sharing one config object never collide;
        # workers alias the user-facing base name to the scoped one.
        cfg = copy.copy(self._config)
        base = getattr(cfg, "group_name", None)
        if base:
            cfg.group_name = f"{base}~{self._run_id}"
        self._started_config = cfg
        cfg.on_start(self.worker_group)

    def start_training(self, train_func: Callable, config: Optional[Dict],
                       checkpoint: Optional[Dict] = None):
        import ray_tpu
        refs = [
            self.worker_group.execute_single_async(
                rank, _start_session_on_worker, self._run_id, train_func,
                config or {}, rank, self._num_workers, checkpoint)
            for rank in range(self._num_workers)]
        ray_tpu.get(refs)

    def get_next_results(self, checkpoint_handler=None
                         ) -> List[TrainingResult]:
        """One report/done per worker, in rank order. Checkpoint events
        are consumed eagerly via ``checkpoint_handler(rank, data)`` so
        report rounds stay aligned across workers even when some ranks
        interleave save_checkpoint with report (reference:
        get_next_results pairs results by type). Raises on the first
        worker error. Once every worker is "done" the same final results
        are returned on every poll."""
        import time
        import ray_tpu
        results: List[TrainingResult] = []
        for r in range(self._num_workers):
            deadline = time.monotonic() + 600.0
            while True:
                res = ray_tpu.get(self.worker_group.execute_single_async(
                    r, _get_next_on_worker, self._run_id, r))
                if res.type == "error":
                    raise TrainBackendError(str(res.data)) from res.data
                if res.type == "timeout":
                    # A hung worker must surface, not spin silently.
                    if time.monotonic() > deadline:
                        raise TrainBackendError(
                            f"worker rank {r} produced no result within "
                            "600s (hung train function?)")
                    continue
                if res.type == "checkpoint":
                    if checkpoint_handler is not None:
                        checkpoint_handler(r, res.data)
                    continue
                results.append(res)
                break
        return results

    def shutdown(self):
        if self.worker_group is not None:
            import ray_tpu
            self._started_config.on_shutdown(self.worker_group)
            try:
                ray_tpu.get([
                    self.worker_group.execute_single_async(
                        r, _drop_session_on_worker, self._run_id, r)
                    for r in range(self._num_workers)])
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
