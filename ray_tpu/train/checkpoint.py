"""Checkpoint management for Train.

Parity: reference ``python/ray/train/checkpoint.py`` —
``CheckpointStrategy`` (num_to_keep, score attribute/order) and the
``CheckpointManager`` that persists rank-0 checkpoints to disk and
tracks the best one.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class CheckpointStrategy:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"


class CheckpointManager:
    def __init__(self, run_dir: Optional[str] = None,
                 strategy: Optional[CheckpointStrategy] = None):
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="ray_tpu_train_")
        self.strategy = strategy or CheckpointStrategy()
        self._checkpoints: List[Dict[str, Any]] = []  # {path, score, id}
        self._next_id = 0
        self.latest_checkpoint: Optional[Dict] = None

    def process_checkpoint(self, checkpoint: Dict) -> str:
        """Persist a (rank-0) checkpoint dict; returns its path."""
        os.makedirs(self.run_dir, exist_ok=True)
        cid = self._next_id
        self._next_id += 1
        path = os.path.join(self.run_dir, f"checkpoint_{cid:06d}.pkl")
        with open(path, "wb") as f:
            pickle.dump(checkpoint, f)
        self.latest_checkpoint = checkpoint
        score = None
        attr = self.strategy.checkpoint_score_attribute
        if attr is not None and attr in checkpoint:
            score = checkpoint[attr]
        self._checkpoints.append({"path": path, "score": score, "id": cid})
        self._evict()
        return path

    def _evict(self):
        keep = self.strategy.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        attr = self.strategy.checkpoint_score_attribute
        if attr is None:
            victims = self._checkpoints[:-keep]
            self._checkpoints = self._checkpoints[-keep:]
        else:
            reverse = self.strategy.checkpoint_score_order == "max"
            ranked = sorted(
                self._checkpoints,
                key=lambda c: (c["score"] is not None, c["score"]),
                reverse=reverse)
            self._checkpoints = ranked[:keep]
            victims = ranked[keep:]
        for v in victims:
            try:
                os.remove(v["path"])
            except OSError:
                pass

    @property
    def best_checkpoint_path(self) -> Optional[str]:
        attr = self.strategy.checkpoint_score_attribute
        scored = [c for c in self._checkpoints if c["score"] is not None]
        if attr is None or not scored:
            return self._checkpoints[-1]["path"] if self._checkpoints \
                else None
        reverse = self.strategy.checkpoint_score_order == "max"
        return sorted(scored, key=lambda c: c["score"],
                      reverse=reverse)[0]["path"]

    @staticmethod
    def load(path: str) -> Dict:
        with open(path, "rb") as f:
            return pickle.load(f)
