"""Trainer: the user-facing distributed training entry point.

Parity: reference ``python/ray/train/trainer.py`` — ``Trainer(backend,
num_workers, use_gpu, resources_per_worker)``; ``start()`` brings up the
worker gang, ``run(train_func, config, callbacks, checkpoint,
checkpoint_strategy)`` drives the report loop and returns one result per
worker; ``run_iterator`` yields intermediate results;
``latest_checkpoint`` / ``best_checkpoint_path`` expose checkpoints;
``to_tune_trainable`` bridges into Tune.
"""

from __future__ import annotations

import tempfile
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.train.backend import (BackendConfig, BackendExecutor, JaxConfig,
                                   TorchConfig)
from ray_tpu.train.callbacks import TrainingCallback
from ray_tpu.train.checkpoint import CheckpointManager, CheckpointStrategy

_BACKENDS = {"jax": JaxConfig, "torch": TorchConfig, "base": BackendConfig}


class Trainer:
    def __init__(self, backend: Union[str, BackendConfig] = "jax",
                 num_workers: int = 1,
                 use_tpu: bool = False,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 logdir: Optional[str] = None):
        if isinstance(backend, str):
            if backend not in _BACKENDS:
                raise ValueError(
                    f"unknown backend {backend!r}; one of {list(_BACKENDS)}")
            backend = _BACKENDS[backend]()
        resources = dict(resources_per_worker or {})
        num_cpus = resources.pop("CPU", 1)
        num_tpus = resources.pop("TPU", 1 if use_tpu else 0)
        self._executor = BackendExecutor(
            backend, num_workers=num_workers,
            num_cpus_per_worker=num_cpus, num_tpus_per_worker=num_tpus,
            additional_resources_per_worker=resources or None)
        self._num_workers = num_workers
        self.logdir = logdir or tempfile.mkdtemp(prefix="ray_tpu_train_")
        self._checkpoint_manager: Optional[CheckpointManager] = None
        self._started = False

    # ------------------------------------------------------------------
    def start(self):
        if not self._started:
            self._executor.start()
            self._started = True

    def shutdown(self):
        if self._started:
            self._executor.shutdown()
            self._started = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.shutdown()

    # ------------------------------------------------------------------
    def run(self, train_func: Callable, config: Optional[Dict] = None,
            callbacks: Optional[List[TrainingCallback]] = None,
            checkpoint: Optional[Dict] = None,
            checkpoint_strategy: Optional[CheckpointStrategy] = None
            ) -> List[Any]:
        """Run to completion; returns the train_func return values,
        one per worker in rank order."""
        for _ in self.run_iterator(train_func, config, callbacks,
                                   checkpoint, checkpoint_strategy):
            pass
        return self._finals

    def run_iterator(self, train_func: Callable,
                     config: Optional[Dict] = None,
                     callbacks: Optional[List[TrainingCallback]] = None,
                     checkpoint: Optional[Dict] = None,
                     checkpoint_strategy: Optional[CheckpointStrategy] = None):
        """Yields one list of per-worker report dicts per report round
        (reference TrainingIterator)."""
        self.start()
        callbacks = callbacks or []
        self._checkpoint_manager = CheckpointManager(
            run_dir=self.logdir, strategy=checkpoint_strategy)
        for cb in callbacks:
            cb.start_training(self.logdir, config or {})
        error = False
        self._finals = [None] * self._num_workers
        def on_checkpoint(rank, data):
            if rank == 0:
                self._checkpoint_manager.process_checkpoint(data)

        try:
            self._executor.start_training(train_func, config, checkpoint)
            while True:
                results = self._executor.get_next_results(on_checkpoint)
                if all(r.type == "done" for r in results):
                    self._finals = [r.data for r in results]
                    break
                reports = [r.data if r.type == "report" else {}
                           for r in results]
                if any(r.type == "report" for r in results):
                    for cb in callbacks:
                        cb.handle_result(reports)
                    yield reports
        except BaseException:
            error = True
            raise
        finally:
            for cb in callbacks:
                cb.finish_training(error=error)

    # ------------------------------------------------------------------
    @property
    def latest_checkpoint(self) -> Optional[Dict]:
        cm = self._checkpoint_manager
        return cm.latest_checkpoint if cm else None

    @property
    def best_checkpoint_path(self) -> Optional[str]:
        cm = self._checkpoint_manager
        return cm.best_checkpoint_path if cm else None

    def load_checkpoint_from_path(self, path: str) -> Dict:
        return CheckpointManager.load(path)

    # ------------------------------------------------------------------
    def to_tune_trainable(self, train_func: Callable) -> Callable:
        """A Tune-compatible function trainable that runs this trainer's
        gang inside the trial (reference trainer.py to_tune_trainable)."""
        executor_args = self._executor._worker_args
        backend = self._executor._config
        num_workers = self._num_workers

        def trainable(config):
            from ray_tpu import tune
            executor = BackendExecutor(backend, **executor_args)
            executor.start()
            try:
                executor.start_training(train_func, config)
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    reports = [r.data for r in results
                               if r.type == "report"]
                    if reports:
                        tune.report(**reports[0])
                    if all(r.type == "done" for r in results):
                        break
            finally:
                executor.shutdown()
        return trainable
