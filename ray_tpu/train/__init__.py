"""ray_tpu.train: distributed training orchestration.

Parity: reference ``python/ray/train/`` — ``Trainer`` (trainer.py) ->
``BackendExecutor`` (backend.py) -> ``WorkerGroup`` of actors
(worker_group.py); per-worker ``session`` with ``report``/``checkpoint``
(session.py); callbacks (callbacks/). The reference's backends wire up
torch DDP / TF MultiWorkerMirrored process groups; here the first-class
backend is **JAX SPMD** (collective group over the device mesh), with a
torch CPU backend for parity.
"""

from ray_tpu.train.backend import (  # noqa: F401
    BackendConfig, JaxConfig, TorchConfig)
from ray_tpu.train.callbacks import (  # noqa: F401
    JsonLoggerCallback, PrintCallback, TrainingCallback)
from ray_tpu.train.checkpoint import CheckpointStrategy  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    local_rank, load_checkpoint, report, save_checkpoint, world_rank,
    world_size)
from ray_tpu.train.trainer import Trainer  # noqa: F401
from ray_tpu.train.worker_group import WorkerGroup  # noqa: F401

__all__ = [
    "BackendConfig", "CheckpointStrategy", "JaxConfig", "JsonLoggerCallback",
    "PrintCallback", "TorchConfig", "Trainer", "TrainingCallback",
    "WorkerGroup", "load_checkpoint", "local_rank", "report",
    "save_checkpoint", "world_rank", "world_size",
]
