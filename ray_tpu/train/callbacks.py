"""Training callbacks.

Parity: reference ``python/ray/train/callbacks/`` —
``TrainingCallback`` hooks (start_training / handle_result /
finish_training), ``JsonLoggerCallback`` (results.json lines),
``PrintCallback``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


class TrainingCallback:
    def start_training(self, logdir: str, config: Dict[str, Any]):
        pass

    def handle_result(self, results: List[Dict[str, Any]]):
        """Called once per report round with one dict per worker."""

    def finish_training(self, error: bool = False):
        pass


class PrintCallback(TrainingCallback):
    def handle_result(self, results):
        print(results)


class JsonLoggerCallback(TrainingCallback):
    def __init__(self, logdir: Optional[str] = None,
                 filename: str = "results.json"):
        self._logdir = logdir
        self._filename = filename
        self._file = None

    def start_training(self, logdir: str, config):
        path = self._logdir or logdir
        os.makedirs(path, exist_ok=True)
        self.log_path = os.path.join(path, self._filename)
        self._file = open(self.log_path, "w")

    def handle_result(self, results):
        if self._file is not None:
            self._file.write(json.dumps(results, default=str) + "\n")
            self._file.flush()

    def finish_training(self, error: bool = False):
        if self._file is not None:
            self._file.close()
            self._file = None
