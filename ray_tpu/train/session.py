"""Per-worker training session.

Parity: reference ``python/ray/train/session.py`` — thread-local
``Session`` created for each training-function run; ``train.report``
hands metrics to the driver between iterations, ``save_checkpoint``/
``load_checkpoint`` round-trip state, ``world_rank``/``local_rank``/
``world_size`` expose topology. The session feeds an ordered event
queue that the driver drains via actor calls (reference: Session's
result queue consumed by ``get_next``).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional


class TrainingResult:
    __slots__ = ("type", "data")

    def __init__(self, type: str, data):  # noqa: A002
        self.type = type  # "report" | "checkpoint" | "done" | "error"
        self.data = data

    def __repr__(self):
        return f"TrainingResult({self.type}, {self.data!r})"


class Session:
    def __init__(self, training_fn, world_rank: int, local_rank: int,
                 world_size: int, checkpoint: Optional[Dict] = None):
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.world_size = world_size
        self.loaded_checkpoint = checkpoint
        self._queue: "queue.Queue[TrainingResult]" = queue.Queue()
        self._fn = training_fn
        self._thread: Optional[threading.Thread] = None
        self._final: Optional[TrainingResult] = None

    # ---- worker side -----------------------------------------------------
    def start(self):
        # Propagate the actor's execution context into the training
        # thread: collective groups and runtime_context are keyed by the
        # (thread-local) worker context of the actor task that set them up.
        from ray_tpu._private import worker_context
        parent_ctx = worker_context.get_context()

        def run():
            worker_context.set_context(parent_ctx)
            _session_local.session = self
            try:
                result = self._fn()
                self._final = TrainingResult("done", result)
            except BaseException as e:  # noqa: BLE001
                self._final = TrainingResult("error", e)
            finally:
                self._queue.put(self._final)
                _session_local.session = None
        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"train-{self.world_rank}")
        self._thread.start()

    def report(self, **metrics):
        self._queue.put(TrainingResult("report", dict(metrics)))

    def save_checkpoint(self, **checkpoint):
        self._queue.put(TrainingResult("checkpoint", dict(checkpoint)))

    # ---- driver side (via actor RPC) ------------------------------------
    def get_next(self, timeout: float = 300.0) -> TrainingResult:
        """Next event; once finished, keeps returning the final result so
        a driver polling mixed-progress workers never blocks on a
        completed rank."""
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._final is not None:
            return self._final
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return TrainingResult("timeout", None)


_session_local = threading.local()


def get_session() -> Session:
    s = getattr(_session_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No training session active: train.report()/world_rank() are "
            "only valid inside a function passed to Trainer.run().")
    return s


# ---- public API used inside train functions ------------------------------

def report(**metrics):
    get_session().report(**metrics)


def save_checkpoint(**checkpoint):
    get_session().save_checkpoint(**checkpoint)


def load_checkpoint() -> Optional[Dict]:
    return get_session().loaded_checkpoint


def world_rank() -> int:
    return get_session().world_rank


def local_rank() -> int:
    return get_session().local_rank


def world_size() -> int:
    return get_session().world_size
