"""Model zoo built on ray_tpu.ops/parallel."""

from ray_tpu.models.transformer import (  # noqa: F401
    TransformerConfig, forward, init_params, loss_fn, make_train_state,
    make_train_step, param_specs)
