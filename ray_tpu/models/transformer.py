"""Flagship model: a decoder-only transformer, TPU-first.

Design notes (this is the model the framework's Train library and the
graft entry exercise):
  * Pure functional jax — params are a pytree of arrays, the whole train
    step is one ``jit`` over a global ``Mesh``; XLA/GSPMD inserts all
    collectives from the shardings (no hand-written allreduce, unlike the
    reference's Train/torch DDP backend, ``python/ray/train/torch.py``).
  * Megatron-style tensor parallelism over ``tp`` (heads + FFN hidden
    sharded), data parallel over ``dp``, context parallel over ``sp``
    via ring attention (ops/ring_attention.py), sequence-parallel
    activation sharding between blocks.
  * ``lax.scan`` over stacked layer params — one compilation regardless
    of depth; optional ``jax.checkpoint`` rematerialisation.
  * bf16 activations/params with f32 RMSNorm + softmax + Adam moments.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.ops.flash_attention import attention as flash_or_ref_attention
from ray_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: Use ring attention over the "sp" mesh axis when its size > 1.
    context_parallel: bool = True
    #: >0 replaces the dense FFN with a switch-MoE of this many experts
    #: (expert weights shard over the "ep" mesh axis — models/moe.py).
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    #: Switch load-balance auxiliary loss weight (prevents router
    #: collapse onto one expert under top-1 routing).
    moe_aux_coeff: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Dict:
    k_embed, k_layers, k_head = jax.random.split(rng, 3)
    d, h, dh, f, nl = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                       cfg.n_layers)
    init = jax.nn.initializers.normal(0.02)
    lkeys = jax.random.split(k_layers, 6)

    def stacked(key, shape):
        return init(key, (nl,) + shape, jnp.float32).astype(cfg.dtype)

    layers: Dict = {
        "ln1": jnp.ones((nl, d), jnp.float32),
        "ln2": jnp.ones((nl, d), jnp.float32),
        "wq": stacked(lkeys[0], (d, h, dh)),
        "wk": stacked(lkeys[1], (d, h, dh)),
        "wv": stacked(lkeys[2], (d, h, dh)),
        "wo": stacked(lkeys[3], (h, dh, d)),
    }
    if cfg.moe_experts > 0:
        from ray_tpu.models.moe import init_moe_params
        layers["moe"] = init_moe_params(
            jax.random.fold_in(k_layers, 8), nl, d, f,
            cfg.moe_experts, cfg.dtype)
    else:
        layers.update({
            "w1": stacked(lkeys[4], (d, f)),
            "w3": stacked(lkeys[5], (d, f)),
            "w2": stacked(jax.random.fold_in(k_layers, 7), (f, d)),
        })
    return {
        "embed": init(k_embed, (cfg.vocab_size, d), jnp.float32
                      ).astype(cfg.dtype),
        "layers": layers,
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": init(k_head, (d, cfg.vocab_size), jnp.float32
                        ).astype(cfg.dtype),
    }


def param_specs(cfg: TransformerConfig) -> Dict:
    """PartitionSpecs: Megatron TP on heads/FFN-hidden, vocab on
    lm_head; MoE expert weights shard over "ep"."""
    layers: Dict = {
        "ln1": P(None, None),
        "ln2": P(None, None),
        "wq": P(None, None, "tp", None),
        "wk": P(None, None, "tp", None),
        "wv": P(None, None, "tp", None),
        "wo": P(None, "tp", None, None),
    }
    if cfg.moe_experts > 0:
        from ray_tpu.models.moe import moe_param_specs
        layers["moe"] = moe_param_specs()
    else:
        layers.update({
            "w1": P(None, None, "tp"),
            "w3": P(None, None, "tp"),
            "w2": P(None, "tp", None),
        })
    return {
        "embed": P(None, "tp"),
        "layers": layers,
        "ln_f": P(None),
        "lm_head": P(None, "tp"),
    }


def batch_spec() -> P:
    return P("dp", "sp")


def _rms_norm(x, w, eps=1e-5):
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * w).astype(x.dtype)


def _rope(x, positions, theta):
    # x: [B, S, H, D]; rotate pairs.
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.log(theta) *
                    jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _attention_core(q, k, v, mesh, cfg: TransformerConfig):
    if (cfg.context_parallel and mesh is not None and
            mesh.shape.get("sp", 1) > 1):
        from jax.experimental.shard_map import shard_map
        fn = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P("dp", "sp", "tp", None),) * 3,
            out_specs=P("dp", "sp", "tp", None),
            check_rep=False)
        return fn(q, k, v)
    return flash_or_ref_attention(q, k, v, causal=True)


def apply_layer(x, lp, positions, cfg: TransformerConfig, mesh=None):
    """One transformer block on [B, S, D] activations with this
    layer's params ``lp``; returns (x, moe_aux).  Shared by the scan
    forward and the pipeline-parallel stage executor."""
    h = _rms_norm(x, lp["ln1"])
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = _attention_core(q, k, v, mesh, cfg)
    x = x + jnp.einsum("bshk,hkd->bsd", o, lp["wo"])
    h = _rms_norm(x, lp["ln2"])
    aux = jnp.zeros((), jnp.float32)
    if cfg.moe_experts > 0:
        from ray_tpu.models.moe import aux_load_balance_loss, moe_ffn
        x = x + moe_ffn(h, lp["moe"], cfg.moe_experts,
                        cfg.moe_capacity_factor, mesh)
        aux = aux_load_balance_loss(h, lp["moe"]["wr"],
                                    cfg.moe_experts)
    else:
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, lp["w1"]))
        up = jnp.einsum("bsd,df->bsf", h, lp["w3"])
        x = x + jnp.einsum("bsf,fd->bsd", gate * up, lp["w2"])
    if mesh is not None:
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None)))
    return x, aux


def forward(params: Dict, tokens: jax.Array, cfg: TransformerConfig,
            mesh=None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V]."""
    logits, _aux = forward_with_aux(params, tokens, cfg, mesh)
    return logits


def forward_with_aux(params: Dict, tokens: jax.Array,
                     cfg: TransformerConfig, mesh=None):
    """Like :func:`forward` but also returns the mean per-layer MoE
    load-balance auxiliary (0 for dense models)."""
    B, S = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)     # [B, S, D]
    if mesh is not None:
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None)))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))

    def layer(carry, lp):
        x, aux = carry
        x, layer_aux = apply_layer(x, lp, positions, cfg, mesh)
        return (x, aux + layer_aux), None

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer
    (x, aux), _ = jax.lax.scan(lambda c, lp: layer_fn(c, lp),
                               (x, jnp.zeros((), jnp.float32)),
                               params["layers"])
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"])
    return logits, aux / max(1, cfg.n_layers)


def loss_fn(params: Dict, batch: Dict, cfg: TransformerConfig,
            mesh=None) -> jax.Array:
    """Next-token cross entropy (+ MoE load-balance auxiliary when
    experts are on: without it, top-1 routing collapses onto one
    expert and over-capacity tokens get dropped en masse).
    batch = {"tokens": [B, S+1] int32}."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward_with_aux(params, inputs, cfg, mesh)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1).squeeze(-1)
    loss = jnp.mean(logz - gold)
    if cfg.moe_experts > 0 and cfg.moe_aux_coeff > 0:
        loss = loss + cfg.moe_aux_coeff * aux
    return loss


# ---------------------------------------------------------------------------
# Train state + step factory (used by ray_tpu.train and the graft entry).
# ---------------------------------------------------------------------------

def make_train_state(rng, cfg: TransformerConfig, mesh=None,
                     learning_rate: float = 3e-4,
                     specs_override: Optional[Dict] = None):
    import optax
    tx = optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=0.1)
    params = init_params(rng, cfg)
    opt_state = tx.init(params)
    state = {"params": params, "opt": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    if mesh is not None:
        specs = specs_override or param_specs(cfg)
        state_specs = {
            "params": specs,
            "opt": jax.tree.map(
                lambda _: P(), opt_state,
                is_leaf=lambda x: isinstance(x, jnp.ndarray)),
            "step": P(),
        }
        # Adam moments mirror the param tree's specs.
        state_specs["opt"] = _opt_specs(opt_state, specs)
        state = jax.device_put(
            state, jax.tree.map(
                lambda s: NamedSharding(mesh, s), state_specs,
                is_leaf=lambda x: isinstance(x, P)))
    return state, tx


def _opt_specs(opt_state, param_spec_tree):
    """Mirror param specs onto the Adam moment trees, P() elsewhere."""
    def one(entry):
        if hasattr(entry, "mu") and hasattr(entry, "nu"):
            return type(entry)(count=P(), mu=param_spec_tree,
                               nu=param_spec_tree)
        return jax.tree.map(lambda _: P(), entry)
    return tuple(one(e) for e in opt_state)


def make_train_step(cfg: TransformerConfig, tx, mesh=None,
                    loss_override=None):
    """``loss_override(params, batch)`` substitutes the plain loss
    (used by the pipeline-parallel schedule)."""
    def train_step(state, batch):
        compute = loss_override or (
            lambda p, b: loss_fn(p, b, cfg, mesh))
        loss, grads = jax.value_and_grad(
            lambda p: compute(p, batch))(state["params"])
        updates, new_opt = tx.update(grads, state["opt"], state["params"])
        new_params = jax.tree.map(
            lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
            state["params"], updates)
        new_state = {"params": new_params, "opt": new_opt,
                     "step": state["step"] + 1}
        metrics = {"loss": loss,
                   "grad_norm": optax_global_norm(grads)}
        return new_state, metrics

    donate = (0,)
    return jax.jit(train_step, donate_argnums=donate)


def optax_global_norm(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))
