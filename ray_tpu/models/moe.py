"""Mixture-of-experts FFN with expert parallelism over an ``ep`` mesh
axis.

TPU-first design (GShard/Switch recipe, the scaling-book EP chapter's
shape): top-1 router, capacity-bounded dense dispatch/combine einsums —
everything is static-shaped matmuls and one-hots, so XLA lays the
dispatch as all-to-all over the ``ep`` axis when the expert dimension
is sharded there.  The reference framework has no MoE at all (SURVEY
§5.7 — parallelism beyond DP is an extension our substrate makes
natural).

Per layer, with T = B*S tokens, E experts, capacity C:
    probs   = softmax(x @ wr)                        [T, E]
    choice  = argmax_E                               (switch top-1)
    pos     = rank of each token within its expert   (cumsum one-hot)
    disp    = onehot(choice) & (pos < C)             [T, E, C]
    ex_in   = einsum('tec,td->ecd', disp, x)         (all-to-all in)
    ex_out  = silu(ex_in @ w1_e) * (ex_in @ w3_e) @ w2_e   per expert
    y       = einsum('tec,ecd->td', disp * gate, ex_out)   (back)
Tokens beyond capacity are dropped (residual passes them through) —
standard Switch behavior.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def init_moe_params(rng: jax.Array, n_layers: int, d_model: int,
                    d_ff: int, n_experts: int, dtype) -> Dict:
    init = jax.nn.initializers.normal(0.02)
    keys = jax.random.split(rng, 4)

    def stacked(key, shape):
        return init(key, (n_layers, *shape), jnp.float32).astype(dtype)

    return {
        "wr": stacked(keys[0], (d_model, n_experts)),
        "w1": stacked(keys[1], (n_experts, d_model, d_ff)),
        "w3": stacked(keys[2], (n_experts, d_model, d_ff)),
        "w2": stacked(keys[3], (n_experts, d_ff, d_model)),
    }


def moe_param_specs() -> Dict:
    """Experts sharded over ``ep``; router replicated."""
    return {
        "wr": P(None, None),
        "w1": P(None, "ep", None, None),
        "w3": P(None, "ep", None, None),
        "w2": P(None, "ep", None, None),
    }


def moe_ffn(x: jax.Array, lp: Dict, n_experts: int,
            capacity_factor: float, mesh=None) -> jax.Array:
    """One MoE FFN block: x [B, S, D] -> [B, S, D] (residual NOT
    included).  ``lp`` holds this layer's wr/w1/w3/w2."""
    B, S, D = x.shape
    T = B * S
    capacity = max(1, int(capacity_factor * T / n_experts))
    xt = x.reshape(T, D)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        lp["wr"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    choice = jnp.argmax(probs, axis=-1)                   # [T]
    gate = jnp.max(probs, axis=-1)                        # [T]
    onehot = jax.nn.one_hot(choice, n_experts,
                            dtype=jnp.float32)            # [T, E]
    # Position of each token within its chosen expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot    # excl. [T, E]
    within = pos < capacity
    disp = onehot * within                                # [T, E]
    slot = jax.nn.one_hot(pos.sum(axis=-1).astype(jnp.int32),
                          capacity, dtype=jnp.float32)    # [T, C]
    dispatch = jnp.einsum("te,tc->tec", disp, slot)       # [T, E, C]
    combine = dispatch * gate[:, None, None]
    ex_in = jnp.einsum("tec,td->ecd", dispatch,
                       xt.astype(jnp.float32))            # [E, C, D]
    if mesh is not None and "ep" in mesh.axis_names:
        # Experts over ep AND capacity rows over dp: capacity slots are
        # independent, so dp shards each run 1/dp of every expert's
        # matmuls instead of replicating the full global-capacity
        # compute per replica.
        ex_in = jax.lax.with_sharding_constraint(
            ex_in, NamedSharding(mesh, P("ep", "dp", None)))
    ex_in = ex_in.astype(x.dtype)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", ex_in, lp["w1"])) * \
        jnp.einsum("ecd,edf->ecf", ex_in, lp["w3"])
    ex_out = jnp.einsum("ecf,efd->ecd", h, lp["w2"])      # [E, C, D]
    if mesh is not None and "ep" in mesh.axis_names:
        ex_out = jax.lax.with_sharding_constraint(
            ex_out, NamedSharding(mesh, P("ep", "dp", None)))
    y = jnp.einsum("tec,ecd->td", combine,
                   ex_out.astype(jnp.float32))            # [T, D]
    return y.astype(x.dtype).reshape(B, S, D)


def aux_load_balance_loss(x: jax.Array, wr: jax.Array,
                          n_experts: int) -> jax.Array:
    """Switch load-balance auxiliary loss: E * sum_e f_e * p_e, where
    f_e = fraction of tokens routed to e, p_e = mean router prob."""
    T = x.shape[0] * x.shape[1]
    xt = x.reshape(T, -1)
    probs = jax.nn.softmax(
        jnp.einsum("td,de->te", xt.astype(jnp.float32),
                   wr.astype(jnp.float32)), axis=-1)
    choice = jax.nn.one_hot(jnp.argmax(probs, axis=-1), n_experts,
                            dtype=jnp.float32)
    f = jnp.mean(choice, axis=0)
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(f * p)
