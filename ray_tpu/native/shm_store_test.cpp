// Concurrency + lifecycle test binary for the native shm store.
//
// Parity: reference plasma's gtest/valgrind suites
// (src/ray/object_manager/plasma/test/) and the sanitizer CI configs
// (TSAN/ASAN bazel configs, SURVEY.md §5.2).  Built and executed by
// tests/test_native_store.py under -fsanitize=address,undefined and
// -fsanitize=thread: data races on the object table / allocator /
// LRU clock and heap errors in the eviction path surface here.
//
// Exercises through the same C ABI Python uses: put/get/pin/unpin/
// delete (incl. deferred free), create/seal, choose_victims — from
// several threads against one store.

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* store_open(const char* name, uint64_t capacity);
void store_close(void* s);
int64_t store_put(void* s, const uint8_t* key, uint32_t keylen,
                  const uint8_t* data, uint64_t size);
int64_t store_create(void* s, const uint8_t* key, uint32_t keylen,
                     uint64_t size);
int store_seal(void* s, const uint8_t* key, uint32_t keylen);
int store_get(void* s, const uint8_t* key, uint32_t keylen,
              uint64_t* offset, uint64_t* size);
int store_delete(void* s, const uint8_t* key, uint32_t keylen);
int store_pin(void* s, const uint8_t* key, uint32_t keylen);
int store_unpin(void* s, const uint8_t* key, uint32_t keylen);
int store_choose_victims(void* s, uint64_t needed, uint8_t* out,
                         uint32_t out_cap, uint64_t* covered);
uint64_t store_used(void* s);
uint64_t store_num_objects(void* s);
uint64_t store_capacity(void* s);
uint64_t store_largest_free(void* s);
}

namespace {

std::atomic<int> failures{0};

#define CHECK(cond)                                                  \
  do {                                                               \
    if (!(cond)) {                                                   \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,  \
                   __LINE__, #cond);                                 \
      failures.fetch_add(1);                                         \
    }                                                                \
  } while (0)

std::string Key(int worker, int i) {
  return "w" + std::to_string(worker) + "-" + std::to_string(i);
}

void Worker(void* store, int id, int iters) {
  std::vector<uint8_t> payload(4096, static_cast<uint8_t>(id));
  for (int i = 0; i < iters; i++) {
    std::string key = Key(id, i);
    const uint8_t* kb = reinterpret_cast<const uint8_t*>(key.data());
    uint32_t kl = static_cast<uint32_t>(key.size());
    int64_t off = store_put(store, kb, kl, payload.data(),
                            payload.size());
    if (off == -1) {
      // OOM: evict something (any thread may race us — fine).
      uint8_t buf[1 << 14];
      uint64_t covered = 0;
      int n = store_choose_victims(store, 64 * 1024, buf, sizeof(buf),
                                   &covered);
      uint32_t pos = 0;
      for (int v = 0; v < n; v++) {
        uint32_t len;
        std::memcpy(&len, buf + pos, 4);
        store_delete(store, buf + pos + 4, len);
        pos += 4 + len;
      }
      continue;
    }
    uint64_t o = 0, sz = 0;
    if (store_get(store, kb, kl, &o, &sz) == 0) {
      CHECK(sz == payload.size());
      // Pin, delete (defers), read metadata gone, unpin (frees).
      if (store_pin(store, kb, kl) == 0) {
        CHECK(store_delete(store, kb, kl) == 0);
        CHECK(store_get(store, kb, kl, &o, &sz) == -1);
        CHECK(store_unpin(store, kb, kl) == 0);
      }
    }
    // Create/seal lifecycle on a second key.
    std::string key2 = key + "-c";
    const uint8_t* kb2 = reinterpret_cast<const uint8_t*>(key2.data());
    uint32_t kl2 = static_cast<uint32_t>(key2.size());
    int64_t off2 = store_create(store, kb2, kl2, 512);
    if (off2 >= 0) {
      // Pin across seal: once sealed, any OOM-pressed peer may evict
      // an UNPINNED object at will, so the get below would race.
      CHECK(store_pin(store, kb2, kl2) == 0);
      CHECK(store_get(store, kb2, kl2, &o, &sz) == -1);  // unsealed
      CHECK(store_seal(store, kb2, kl2) == 0);
      CHECK(store_get(store, kb2, kl2, &o, &sz) == 0);
      CHECK(store_unpin(store, kb2, kl2) == 0);
      store_delete(store, kb2, kl2);
    }
  }
}

// Retriable-OOM create flow (create_request_queue parity): drive the
// segment to OOM with large create/seal reservations, then recover via
// choose_victims + delete (the spill-free path: the Python side copies
// the bytes to disk BEFORE delete; here we only exercise the native
// free) and retry the create.  Every OOM must be a -1 code, never an
// abort, and after eviction the create must eventually succeed.
void OomWorker(void* store, int id, int iters) {
  const uint64_t big = 256 * 1024;
  for (int i = 0; i < iters; i++) {
    std::string key = "oom-" + Key(id, i);
    const uint8_t* kb = reinterpret_cast<const uint8_t*>(key.data());
    uint32_t kl = static_cast<uint32_t>(key.size());
    int64_t off = store_create(store, kb, kl, big);
    int attempts = 0;
    while (off == -1 && attempts++ < 64) {
      // Diagnostic surface must stay consistent under concurrency.
      CHECK(store_largest_free(store) <= store_capacity(store));
      uint8_t buf[1 << 14];
      uint64_t covered = 0;
      int n = store_choose_victims(store, big * 2, buf, sizeof(buf),
                                   &covered);
      uint32_t pos = 0;
      for (int v = 0; v < n; v++) {
        uint32_t len;
        std::memcpy(&len, buf + pos, 4);
        // Spill-free path: a pinned victim must survive the delete
        // until unpin (another thread may be mid-read through its
        // mapping); an unpinned one frees immediately.
        if (store_pin(store, buf + pos + 4, len) == 0) {
          store_delete(store, buf + pos + 4, len);
          store_unpin(store, buf + pos + 4, len);
        } else {
          store_delete(store, buf + pos + 4, len);
        }
        pos += 4 + len;
      }
      off = store_create(store, kb, kl, big);
    }
    if (off >= 0) {
      // Pin across seal: a concurrent evictor may take any unpinned
      // sealed object between our seal and get.
      CHECK(store_pin(store, kb, kl) == 0);
      CHECK(store_seal(store, kb, kl) == 0);
      uint64_t o = 0, sz = 0;
      CHECK(store_get(store, kb, kl, &o, &sz) == 0);
      CHECK(sz == big);
      CHECK(store_unpin(store, kb, kl) == 0);
    }
  }
}

#ifdef GRAFT_SPILL_CALLBACKS
// Spill-callback OOM/evict path (graftcheck PR satellite): the Python
// LocalObjectManager reacts to OOM by COPYING the victim's bytes out
// through its own segment mapping ("spill write") while the victim is
// pinned, and only then deleting it.  Simulated here natively so TSan
// sweeps the contract the Python side relies on: a pinned victim's
// payload bytes must stay readable (no allocator reuse racing the
// read) until unpin, even while OOM-pressed peers churn create/seal/
// evict against the same segment.
void SpillOomWorker(void* store, const uint8_t* seg_base, int id,
                    int iters) {
  const uint64_t big = 192 * 1024;
  std::vector<uint8_t> spill_buf(big);
  for (int i = 0; i < iters; i++) {
    std::string key = "spill-" + Key(id, i);
    const uint8_t* kb = reinterpret_cast<const uint8_t*>(key.data());
    uint32_t kl = static_cast<uint32_t>(key.size());
    int64_t off = store_create(store, kb, kl, big);
    int attempts = 0;
    while (off == -1 && attempts++ < 64) {
      uint8_t buf[1 << 14];
      uint64_t covered = 0;
      int n = store_choose_victims(store, big * 2, buf, sizeof(buf),
                                   &covered);
      uint32_t pos = 0;
      for (int v = 0; v < n; v++) {
        uint32_t len;
        std::memcpy(&len, buf + pos, 4);
        const uint8_t* vkey = buf + pos + 4;
        // Spill callback: pin, locate, copy the payload OUT of the
        // segment, then delete (deferred free) and unpin (real free).
        if (store_pin(store, vkey, len) == 0) {
          uint64_t vo = 0, vs = 0;
          if (store_get(store, vkey, len, &vo, &vs) == 0) {
            uint64_t take = vs < spill_buf.size() ? vs : spill_buf.size();
            std::memcpy(spill_buf.data(), seg_base + vo, take);
          }
          store_delete(store, vkey, len);
          store_unpin(store, vkey, len);
        } else {
          store_delete(store, vkey, len);
        }
        pos += 4 + len;
      }
      off = store_create(store, kb, kl, big);
    }
    if (off >= 0) {
      CHECK(store_pin(store, kb, kl) == 0);
      CHECK(store_seal(store, kb, kl) == 0);
      uint64_t o = 0, sz = 0;
      CHECK(store_get(store, kb, kl, &o, &sz) == 0);
      CHECK(sz == big);
      CHECK(store_unpin(store, kb, kl) == 0);
    }
  }
}
#endif  // GRAFT_SPILL_CALLBACKS

}  // namespace

int main() {
  std::string name = "/raytpu-santest-" + std::to_string(getpid());
  void* store = store_open(name.c_str(), 8 * 1024 * 1024);
  if (store == nullptr) {
    std::fprintf(stderr, "store_open failed\n");
    return 2;
  }
  const int kThreads = 8, kIters = 400;
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back(Worker, store, t, kIters);
  }
  // Concurrent OOM-pressure workers: retriable-OOM create + evict +
  // retry against the same segment the lifecycle workers churn.
  for (int t = 0; t < 4; t++) {
    threads.emplace_back(OomWorker, store, kThreads + t, 64);
  }
#ifdef GRAFT_SPILL_CALLBACKS
  // Spill-simulating evictors read victim payloads through their own
  // mapping of the segment (exactly how the Python spill path reads;
  // the file is `capacity` bytes, offsets absolute).
  int seg_fd = shm_open(name.c_str(), O_RDWR, 0600);
  if (seg_fd < 0) {
    std::fprintf(stderr, "shm_open for spill mapping failed\n");
    return 2;
  }
  const uint8_t* seg_base = static_cast<const uint8_t*>(
      mmap(nullptr, store_capacity(store), PROT_READ, MAP_SHARED,
           seg_fd, 0));
  close(seg_fd);
  if (seg_base == MAP_FAILED) {
    std::fprintf(stderr, "mmap for spill mapping failed\n");
    return 2;
  }
  for (int t = 0; t < 4; t++) {
    threads.emplace_back(SpillOomWorker, store, seg_base,
                         kThreads + 4 + t, 48);
  }
#endif
  for (auto& th : threads) th.join();
  std::fprintf(stderr, "objects=%llu used=%llu failures=%d\n",
               static_cast<unsigned long long>(store_num_objects(store)),
               static_cast<unsigned long long>(store_used(store)),
               failures.load());
  store_close(store);
  return failures.load() == 0 ? 0 : 1;
}
