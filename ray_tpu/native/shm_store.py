"""ctypes binding for the native shared-memory store.

Builds ``shm_store.cpp`` with g++ on first use (cached .so).  Reads are
zero-copy: Python mmaps the same shm segment and returns memoryview
slices at the (offset, size) handles the C++ side hands out — the same
client model as plasma's mmap'd object views
(src/ray/object_manager/plasma/client.cc).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
import uuid
from typing import Optional

_BUILD_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(__file__), "shm_store.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_build", "libshm_store.so")


def _build() -> str:
    with _BUILD_LOCK:
        if os.path.exists(_SO) and \
                os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", _SO, "-lrt"]
        subprocess.run(cmd, check=True, capture_output=True)
        return _SO


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build())
    lib.store_open.restype = ctypes.c_void_p
    lib.store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.store_close.argtypes = [ctypes.c_void_p]
    lib.store_put.restype = ctypes.c_int64
    lib.store_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32, ctypes.c_char_p,
                              ctypes.c_uint64]
    lib.store_get.restype = ctypes.c_int
    lib.store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32,
                              ctypes.POINTER(ctypes.c_uint64),
                              ctypes.POINTER(ctypes.c_uint64)]
    lib.store_delete.restype = ctypes.c_int
    lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32]
    lib.store_used.restype = ctypes.c_uint64
    lib.store_used.argtypes = [ctypes.c_void_p]
    lib.store_capacity.restype = ctypes.c_uint64
    lib.store_capacity.argtypes = [ctypes.c_void_p]
    lib.store_num_objects.restype = ctypes.c_uint64
    lib.store_num_objects.argtypes = [ctypes.c_void_p]
    return lib


class NativeShmStore:
    """One shm segment + object table; zero-copy mmap reads."""

    def __init__(self, capacity: int = 256 * 1024 * 1024,
                 name: Optional[str] = None):
        self._lib = _load()
        self._name = name or f"/raytpu-{uuid.uuid4().hex[:12]}"
        self._handle = self._lib.store_open(self._name.encode(), capacity)
        if not self._handle:
            raise OSError("native shm store open failed")
        # Map the same segment for zero-copy reads.
        fd = os.open(f"/dev/shm{self._name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, capacity)
        finally:
            os.close(fd)
        self.capacity = capacity
        self._closed = False

    def put(self, key: bytes, data: bytes) -> None:
        rc = self._lib.store_put(self._handle, key, len(key), data,
                                 len(data))
        if rc == -1:
            raise MemoryError("native store full")
        if rc == -2:
            return  # idempotent re-put

    def get(self, key: bytes) -> Optional[memoryview]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, key, len(key),
                                 ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return memoryview(self._mm)[off.value:off.value + size.value]

    def delete(self, key: bytes) -> bool:
        return self._lib.store_delete(self._handle, key, len(key)) == 0

    def used_bytes(self) -> int:
        return self._lib.store_used(self._handle)

    def num_objects(self) -> int:
        return self._lib.store_num_objects(self._handle)

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self._mm.close()
            except BufferError:
                pass  # exported memoryviews still alive
            self._lib.store_close(self._handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def open_store(capacity: int = 256 * 1024 * 1024) -> NativeShmStore:
    return NativeShmStore(capacity=capacity)
