"""ctypes binding for the native shared-memory store.

Builds ``shm_store.cpp`` with g++ on first use (cached .so).  Reads are
zero-copy: Python mmaps the same shm segment and returns memoryview
slices at the (offset, size) handles the C++ side hands out — the same
client model as plasma's mmap'd object views
(src/ray/object_manager/plasma/client.cc).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
import uuid
from typing import Optional

_BUILD_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(__file__), "shm_store.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_build", "libshm_store.so")

# tmpfs pages are first-touch, so `df /dev/shm` does not reflect open
# (sparse) segments — a sizing decision based on free space alone
# over-commits, and filling over-committed segments later dies with
# SIGBUS, not a catchable error.  Track this process's outstanding
# segment capacity so sizing (raylet._maybe_native_store) can subtract
# its own reservations.
_RESERVED_LOCK = threading.Lock()
_RESERVED_BYTES = 0

#: ``try_create`` status codes — the retriable-OOM create surface
#: (plasma ``PlasmaError``: OK / ObjectExists / OutOfMemory).  OOM is a
#: CODE, not an exception: the caller's create-request queue retries it
#: as seals/evictions/spills free space instead of unwinding.
CREATE_OK = 0
CREATE_DUPLICATE = 1     # key already present (sealed or mid-write)
CREATE_PENDING = 2       # deleted-pending: freed on last client unpin
CREATE_OOM = 3           # retriable: no block fits right now


def reserved_bytes() -> int:
    """Total capacity of segments currently open in THIS process."""
    with _RESERVED_LOCK:
        return _RESERVED_BYTES


def _reserve(delta: int) -> None:
    global _RESERVED_BYTES
    with _RESERVED_LOCK:
        _RESERVED_BYTES += delta


def _build() -> str:
    with _BUILD_LOCK:
        if os.path.exists(_SO) and \
                os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", _SO, "-lrt"]
        subprocess.run(cmd, check=True, capture_output=True)
        return _SO


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build())
    lib.store_open.restype = ctypes.c_void_p
    lib.store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.store_close.argtypes = [ctypes.c_void_p]
    lib.store_put.restype = ctypes.c_int64
    lib.store_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32, ctypes.c_char_p,
                              ctypes.c_uint64]
    lib.store_get.restype = ctypes.c_int
    lib.store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32,
                              ctypes.POINTER(ctypes.c_uint64),
                              ctypes.POINTER(ctypes.c_uint64)]
    lib.store_delete.restype = ctypes.c_int
    lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32]
    lib.store_used.restype = ctypes.c_uint64
    lib.store_used.argtypes = [ctypes.c_void_p]
    lib.store_capacity.restype = ctypes.c_uint64
    lib.store_capacity.argtypes = [ctypes.c_void_p]
    lib.store_num_objects.restype = ctypes.c_uint64
    lib.store_num_objects.argtypes = [ctypes.c_void_p]
    lib.store_create.restype = ctypes.c_int64
    lib.store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.c_uint64]
    lib.store_seal.restype = ctypes.c_int
    lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32]
    lib.store_pin.restype = ctypes.c_int
    lib.store_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32]
    lib.store_unpin.restype = ctypes.c_int
    lib.store_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.store_choose_victims.restype = ctypes.c_int
    lib.store_choose_victims.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64)]
    lib.store_largest_free.restype = ctypes.c_uint64
    lib.store_largest_free.argtypes = [ctypes.c_void_p]
    return lib


class NativeShmStore:
    """One shm segment + object table; zero-copy mmap reads."""

    def __init__(self, capacity: int = 256 * 1024 * 1024,
                 name: Optional[str] = None):
        self._lib = _load()
        self._name = name or f"/raytpu-{uuid.uuid4().hex[:12]}"
        self._handle = self._lib.store_open(self._name.encode(), capacity)
        if not self._handle:
            raise OSError("native shm store open failed")
        # Map the same segment for zero-copy reads.
        fd = os.open(f"/dev/shm{self._name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, capacity)
        finally:
            os.close(fd)
        self.capacity = capacity
        self._closed = False
        _reserve(capacity)

    def put(self, key: bytes, data: bytes) -> None:
        rc = self._lib.store_put(self._handle, key, len(key), data,
                                 len(data))
        if rc == -1:
            raise MemoryError("native store full")
        if rc == -3:
            # Deleted-pending: a client still holds the old bytes
            # pinned; the key is unusable until the last release.
            raise KeyError("object key awaiting deferred free")
        if rc == -2:
            return  # idempotent re-put

    def get(self, key: bytes) -> Optional[memoryview]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, key, len(key),
                                 ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return memoryview(self._mm)[off.value:off.value + size.value]

    def delete(self, key: bytes) -> bool:
        return self._lib.store_delete(self._handle, key, len(key)) == 0

    def view(self, offset: int, size: int) -> memoryview:
        """Writable view over a reserved block — the create/seal write
        surface for the owning process (clients use AttachedSegment)."""
        return memoryview(self._mm)[offset:offset + size]

    # ---- plasma create/seal lifecycle (client writes through shm) -----
    def try_create(self, key: bytes, size: int):
        """Reserve ``size`` bytes without throwing: returns
        ``(status, offset)`` where status is one of the ``CREATE_*``
        codes and offset is valid only for ``CREATE_OK``.  ``CREATE_OOM``
        is RETRIABLE — the caller's create-request queue evicts/spills
        and retries rather than failing the put
        (create_request_queue.h semantics)."""
        off = self._lib.store_create(self._handle, key, len(key), size)
        if off >= 0:
            return CREATE_OK, int(off)
        if off == -1:
            return CREATE_OOM, -1
        if off == -3:
            return CREATE_PENDING, -1
        return CREATE_DUPLICATE, -1

    def create(self, key: bytes, size: int) -> Optional[int]:
        """Legacy throwing wrapper over :meth:`try_create` (kept for
        direct store users/tests): returns the offset, None on
        duplicate/deleted-pending, raises MemoryError on OOM."""
        status, off = self.try_create(key, size)
        if status == CREATE_OOM:
            raise MemoryError("native store full")
        return off if status == CREATE_OK else None

    def seal(self, key: bytes) -> bool:
        return self._lib.store_seal(self._handle, key, len(key)) == 0

    def locate(self, key: bytes) -> Optional[tuple]:
        """(offset, size) of a sealed object, touching its LRU slot."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, key, len(key),
                                 ctypes.byref(off), ctypes.byref(size))
        return None if rc != 0 else (off.value, size.value)

    def pin(self, key: bytes) -> bool:
        return self._lib.store_pin(self._handle, key, len(key)) == 0

    def unpin(self, key: bytes) -> bool:
        return self._lib.store_unpin(self._handle, key, len(key)) == 0

    def choose_victims(self, needed: int):
        """Best-effort LRU victims toward freeing >= needed bytes;
        empty when nothing is evictable."""
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        covered = ctypes.c_uint64()
        n = self._lib.store_choose_victims(
            self._handle, needed, buf, cap, ctypes.byref(covered))
        if n < 0:
            return []
        keys, pos = [], 0
        raw = buf.raw
        for _ in range(n):
            ln = int.from_bytes(raw[pos:pos + 4], "little")
            keys.append(raw[pos + 4:pos + 4 + ln])
            pos += 4 + ln
        return keys

    @property
    def name(self) -> str:
        return self._name

    def used_bytes(self) -> int:
        return self._lib.store_used(self._handle)

    def largest_free_block(self) -> int:
        """Largest contiguous hole the allocator could hand out right
        now (coalesces the bins first) — OOM diagnostics: total free
        can exceed a request while no single hole fits it."""
        return self._lib.store_largest_free(self._handle)

    def num_objects(self) -> int:
        return self._lib.store_num_objects(self._handle)

    def close(self):
        if not self._closed:
            self._closed = True
            _reserve(-self.capacity)
            try:
                self._mm.close()
            except BufferError:
                pass  # exported memoryviews still alive
            self._lib.store_close(self._handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def open_store(capacity: int = 256 * 1024 * 1024) -> NativeShmStore:
    return NativeShmStore(capacity=capacity)


class AttachedSegment:
    """Client-side mapping of a store segment owned by another process
    (plasma client model, ``plasma/client.cc``): metadata — offsets,
    pins, create/seal — travels over the worker's RPC channel to the
    node; the BYTES are read and written directly through mmaps,
    zero-copy.

    Two mappings: reads go through a READ-ONLY map, so deserialized
    arrays are read-only views (plasma maps client reads read-only for
    the same reason — an in-place ``a += 1`` on a task arg must raise,
    not silently corrupt the shared object); create/seal writes go
    through a separate read-write map."""

    def __init__(self, name: str, capacity: int):
        self.name = name
        self.capacity = capacity
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            self._ro = mmap.mmap(fd, capacity, prot=mmap.PROT_READ)
            self._rw = mmap.mmap(fd, capacity)
        finally:
            os.close(fd)

    def read(self, offset: int, size: int) -> memoryview:
        return memoryview(self._ro)[offset:offset + size]

    def write(self, offset: int, data) -> None:
        self._rw[offset:offset + len(data)] = data

    def view(self, offset: int, size: int) -> memoryview:
        """Writable view over a create-reservation: the worker's
        single-copy return path serializes straight into this."""
        return memoryview(self._rw)[offset:offset + size]

    def close(self):
        for mm in (self._ro, self._rw):
            try:
                mm.close()
            except (BufferError, ValueError):
                pass
