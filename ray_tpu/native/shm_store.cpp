// Shared-memory object store — the native core of the per-node store.
//
// TPU-native equivalent of the reference's Plasma store
// (src/ray/object_manager/plasma/: dlmalloc over mmap'd shm, object table,
// create/seal lifecycle, eviction hooks).  Differences by design:
//   * one flat shm segment with a two-tier allocator: size-class bins
//     (segregated free lists, jemalloc/dlmalloc smallbin spirit) for
//     small/medium blocks and an offset-ordered coalescing free map for
//     large ones — instead of vendored dlmalloc;
//   * the object table is SHARDED: hash(key) picks one of kShards
//     independently-locked maps, so concurrent workers putting returns
//     do not serialize on a single store mutex (the seed store's global
//     lock was the write-path bottleneck);
//   * bulk copies happen OUTSIDE any lock: Put allocates (allocator
//     lock), memcpys into the segment with no lock held, then publishes
//     the entry (shard lock).  Create/Seal expose the same lifecycle to
//     clients writing through their own mappings (plasma/client.cc);
//   * LRU eviction policy (pin counts, victim selection,
//     delete-while-pinned deferred free) is native
//     (eviction_policy.h parity); the spill IO callback stays in the
//     Python LocalObjectManager.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <fcntl.h>
#include <array>
#include <atomic>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <time.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>
#include <algorithm>

namespace {

constexpr int kShards = 16;          // object-table stripes
constexpr uint64_t kAlign = 64;      // block alignment
constexpr uint64_t kBinMax = 1 << 20;  // blocks above 1 MiB skip the bins
constexpr uint64_t kLinearMax = 4096;  // 64 B linear classes up to here
constexpr size_t kBinCap = 64;       // max cached blocks per bin

struct Block {
  uint64_t offset;
  uint64_t size;
};

struct ObjectEntry {
  uint64_t offset;
  uint64_t size;        // payload size
  uint64_t alloc_size;  // rounded block size actually reserved
  bool sealed;
  uint32_t pin_count;
  uint64_t lru_tick;    // global counter value at last touch
  bool deleted;         // delete-while-pinned: freed on last unpin
  uint64_t created_ms;  // monotonic ms at creation (stale-reclaim gate)
};

inline uint64_t NowMs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 +
         static_cast<uint64_t>(ts.tv_nsec) / 1000000;
}

// An unsealed, unpinned entry is reclaimable only once it is OLDER
// than any plausible live write window: every host put, transfer
// writer and worker create/seal leaves its entry unsealed while the
// bulk copy runs, and reclaiming a LIVE reservation would free a block
// another writer is actively filling (segment corruption).  Stale ones
// (crashed client, abort lost) must still be reclaimed or the key is
// poisoned forever.
constexpr uint64_t kStaleReservationMs = 60 * 1000;

inline uint64_t AlignUp(uint64_t n) {
  return (n + kAlign - 1) & ~(kAlign - 1);
}

// Size-class rounding: 64 B linear steps up to 4 KiB, then four classes
// per power-of-two doubling (quarter-pow2, <= 25% internal
// fragmentation) up to kBinMax.  Returns the rounded block size.
inline uint64_t ClassSize(uint64_t n) {
  if (n <= kLinearMax) return AlignUp(n ? n : 1);
  // n in (p, 2p] for the largest power of two p < n.
  uint64_t p = 1ull << (63 - __builtin_clzll(n - 1));
  uint64_t step = p / 4;
  if (step < kAlign) step = kAlign;
  return ((n + step - 1) / step) * step;
}

// Reservation size for a payload: size-class rounded while the block
// can live in a bin, plain 64B alignment above kBinMax (class rounding
// there would waste up to 25% exactly where capacity pressure is
// highest, and those blocks never hit the bins anyway).
inline uint64_t ReserveSize(uint64_t n) {
  if (n == 0) n = 1;
  return n <= kBinMax ? ClassSize(n) : AlignUp(n);
}

// Dense bin index for a CLASS size (result of ClassSize <= kBinMax).
inline int BinIndex(uint64_t cls) {
  if (cls <= kLinearMax) return static_cast<int>(cls / kAlign) - 1;  // 0..63
  int base = static_cast<int>(kLinearMax / kAlign) - 1;  // last linear bin
  uint64_t p = 1ull << (63 - __builtin_clzll(cls - 1));
  uint64_t step = p / 4;
  int doubling = static_cast<int>(63 - __builtin_clzll(p)) - 12;  // p=4096 -> 0
  int within = static_cast<int>(cls / step) - 5;  // cls/step in {5,6,7,8}
  return base + 1 + doubling * 4 + within;
}

constexpr int kBinCount = 64 + 4 * 9 + 4;  // linear + doublings 4K..1M + slack

// Two-tier segment allocator.  Fast path: exact-class reuse from a bin
// (O(1), short critical section).  Slow path: first-fit over the
// offset-ordered coalescing map; bins are flushed into it (coalescing
// then) before reporting OOM, so binning never causes a spurious OOM.
class Allocator {
 public:
  explicit Allocator(uint64_t capacity) { free_by_offset_[0] = capacity; }

  // size must already be a ClassSize/AlignUp result.
  int64_t Allocate(uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    if (size <= kBinMax) {
      auto& bin = bins_[BinIndex(size)];
      if (!bin.empty()) {
        uint64_t off = bin.back().offset;
        bin.pop_back();
        binned_bytes_ -= size;
        return static_cast<int64_t>(off);
      }
    }
    int64_t off = FirstFitLocked(size);
    if (off >= 0) return off;
    FlushBinsLocked();
    return FirstFitLocked(size);
  }

  void Free(uint64_t offset, uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    if (size <= kBinMax) {
      auto& bin = bins_[BinIndex(size)];
      if (bin.size() < kBinCap) {
        bin.push_back(Block{offset, size});
        binned_bytes_ += size;
        return;
      }
    }
    CoalesceLocked(offset, size);
  }

  // Largest allocation the segment could currently satisfy after
  // coalescing everything (diagnostic for the eviction escalation).
  void FlushBins() {
    std::lock_guard<std::mutex> g(mu_);
    FlushBinsLocked();
  }

  // Largest contiguous hole after coalescing the bins — the honest
  // answer to "would a block of size N fit right now?".  Diagnostic
  // path (OOM error context + backpressure decisions), not the hot
  // allocation path, so the full flush+scan cost is acceptable.
  uint64_t LargestFree() {
    std::lock_guard<std::mutex> g(mu_);
    FlushBinsLocked();
    uint64_t best = 0;
    for (const auto& kv : free_by_offset_) {
      if (kv.second > best) best = kv.second;
    }
    return best;
  }

 private:
  int64_t FirstFitLocked(uint64_t size) {
    for (auto it = free_by_offset_.begin(); it != free_by_offset_.end();
         ++it) {
      if (it->second >= size) {
        uint64_t off = it->first;
        uint64_t remaining = it->second - size;
        free_by_offset_.erase(it);
        if (remaining > 0) free_by_offset_[off + size] = remaining;
        return static_cast<int64_t>(off);
      }
    }
    return -1;
  }

  void FlushBinsLocked() {
    for (auto& bin : bins_) {
      for (const Block& b : bin) CoalesceLocked(b.offset, b.size);
      bin.clear();
    }
    binned_bytes_ = 0;
  }

  // Insert with coalescing of adjacent blocks.
  void CoalesceLocked(uint64_t offset, uint64_t size) {
    auto next = free_by_offset_.lower_bound(offset);
    if (next != free_by_offset_.end() && offset + size == next->first) {
      size += next->second;
      next = free_by_offset_.erase(next);
    }
    if (next != free_by_offset_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        prev->second += size;
        return;
      }
    }
    free_by_offset_[offset] = size;
  }

  std::mutex mu_;
  std::map<uint64_t, uint64_t> free_by_offset_;  // offset -> size
  std::array<std::vector<Block>, kBinCount> bins_;
  uint64_t binned_bytes_ = 0;
};

class ShmStore {
 public:
  ShmStore(const char* name, uint64_t capacity)
      : name_(name), capacity_(capacity), alloc_(capacity) {
    fd_ = shm_open(name, O_CREAT | O_RDWR, 0600);
    if (fd_ < 0) throw std::runtime_error("shm_open failed");
    if (ftruncate(fd_, static_cast<off_t>(capacity)) != 0) {
      close(fd_);
      throw std::runtime_error("ftruncate failed");
    }
    base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       fd_, 0));
    if (base_ == MAP_FAILED) {
      close(fd_);
      throw std::runtime_error("mmap failed");
    }
  }

  ~ShmStore() {
    munmap(base_, capacity_);
    close(fd_);
    shm_unlink(name_.c_str());
  }

  // Returns offset, -1 on OOM, -2 if already present, -3 if the key is
  // in deleted-pending state (freed on last unpin; not re-usable yet).
  // The memcpy runs with NO lock held: the block is private until the
  // entry is published into its shard.
  int64_t Put(const std::string& key, const uint8_t* data, uint64_t size) {
    Shard& sh = ShardFor(key);
    {
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.objects.find(key);
      if (it != sh.objects.end()) {
        if (it->second.deleted) return -3;
        if (!it->second.sealed && it->second.pin_count == 0 &&
            NowMs() - it->second.created_ms > kStaleReservationMs) {
          // Stale create-reservation (client write/seal failed long
          // ago): the bytes were never valid — reclaim, write fresh.
          EraseLocked(sh, it);
        } else {
          return -2;
        }
      }
    }
    uint64_t cls = ReserveSize(size);
    int64_t off = alloc_.Allocate(cls);
    if (off < 0) return -1;
    std::memcpy(base_ + off, data, size);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it != sh.objects.end()) {
      // Lost a publish race (concurrent put of the same key): keep the
      // winner, drop our private block.
      alloc_.Free(static_cast<uint64_t>(off), cls);
      return it->second.deleted ? -3 : -2;
    }
    sh.objects[key] = ObjectEntry{
        static_cast<uint64_t>(off), size, cls, true, 0,
        tick_.fetch_add(1, std::memory_order_relaxed) + 1, false,
        NowMs()};
    used_.fetch_add(cls, std::memory_order_relaxed);
    num_objects_.fetch_add(1, std::memory_order_relaxed);
    return off;
  }

  // Create without copying (caller writes through the mapped segment,
  // then seals) — the plasma create/seal lifecycle.
  int64_t Create(const std::string& key, uint64_t size) {
    Shard& sh = ShardFor(key);
    {
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.objects.find(key);
      if (it != sh.objects.end()) {
        if (it->second.deleted) return -3;
        if (!it->second.sealed && it->second.pin_count == 0 &&
            NowMs() - it->second.created_ms > kStaleReservationMs) {
          EraseLocked(sh, it);  // stale (aged-out) reservation: reclaim
        } else {
          return -2;
        }
      }
    }
    uint64_t cls = ReserveSize(size);
    int64_t off = alloc_.Allocate(cls);
    if (off < 0) return -1;
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it != sh.objects.end()) {
      alloc_.Free(static_cast<uint64_t>(off), cls);
      return it->second.deleted ? -3 : -2;
    }
    sh.objects[key] = ObjectEntry{
        static_cast<uint64_t>(off), size, cls, false, 0,
        tick_.fetch_add(1, std::memory_order_relaxed) + 1, false,
        NowMs()};
    used_.fetch_add(cls, std::memory_order_relaxed);
    num_objects_.fetch_add(1, std::memory_order_relaxed);
    return off;
  }

  int Seal(const std::string& key) {
    Shard& sh = ShardFor(key);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it == sh.objects.end()) return -1;
    it->second.sealed = true;
    return 0;
  }

  // Returns (offset, size) through out params; -1 if missing/unsealed.
  // Touches the LRU clock (eviction_policy.h parity: reads refresh).
  int Get(const std::string& key, uint64_t* offset, uint64_t* size) {
    Shard& sh = ShardFor(key);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it == sh.objects.end() || !it->second.sealed ||
        it->second.deleted) {
      return -1;
    }
    it->second.lru_tick = tick_.fetch_add(1, std::memory_order_relaxed) + 1;
    *offset = it->second.offset;
    *size = it->second.size;
    return 0;
  }

  int Pin(const std::string& key) {
    Shard& sh = ShardFor(key);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it == sh.objects.end() || it->second.deleted) return -1;
    it->second.pin_count++;
    return 0;
  }

  int Unpin(const std::string& key) {
    Shard& sh = ShardFor(key);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it == sh.objects.end() || it->second.pin_count == 0) return -1;
    it->second.pin_count--;
    if (it->second.pin_count == 0 && it->second.deleted) {
      EraseLocked(sh, it);
    }
    return 0;
  }

  // LRU victim selection (eviction_policy.h ChooseObjectsToEvict
  // parity): pick least-recently-touched sealed+unpinned objects until
  // >= needed bytes are covered (best effort — fewer bytes when little
  // is evictable; the caller inspects covered_out).  Writes
  // [u32 len][key bytes]* into out; returns #victims, or -2 if the
  // out buffer is too small.  Candidates are gathered shard by shard
  // (each under its own lock), then merged by LRU tick.
  int ChooseVictims(uint64_t needed, uint8_t* out, uint32_t out_cap,
                    uint64_t* covered_out) {
    struct Cand {
      uint64_t tick;
      uint64_t bytes;
      std::string key;
    };
    std::vector<Cand> cand;
    for (Shard& sh : shards_) {
      std::lock_guard<std::mutex> g(sh.mu);
      for (auto& kv : sh.objects) {
        if (kv.second.sealed && kv.second.pin_count == 0 &&
            !kv.second.deleted) {
          cand.push_back(
              Cand{kv.second.lru_tick, kv.second.alloc_size, kv.first});
        }
      }
    }
    std::sort(cand.begin(), cand.end(),
              [](const Cand& a, const Cand& b) { return a.tick < b.tick; });
    uint64_t covered = 0;
    uint32_t pos = 0;
    int n = 0;
    for (auto& c : cand) {
      if (covered >= needed) break;
      if (pos + 4 + c.key.size() > out_cap) return -2;
      uint32_t len = static_cast<uint32_t>(c.key.size());
      std::memcpy(out + pos, &len, 4);
      std::memcpy(out + pos + 4, c.key.data(), c.key.size());
      pos += 4 + len;
      covered += c.bytes;
      n++;
    }
    *covered_out = covered;
    return n;
  }

  int Delete(const std::string& key) {
    Shard& sh = ShardFor(key);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.objects.find(key);
    if (it == sh.objects.end()) return -1;
    if (it->second.pin_count > 0) {
      // Deferred free (plasma release semantics): a client still reads
      // through its mapping; hide the object and free on last unpin.
      it->second.deleted = true;
      return 0;
    }
    EraseLocked(sh, it);
    return 0;
  }

  uint64_t LargestFreeBlock() { return alloc_.LargestFree(); }

  uint64_t Used() const { return used_.load(std::memory_order_relaxed); }
  uint64_t Capacity() const { return capacity_; }
  uint64_t NumObjects() const {
    return num_objects_.load(std::memory_order_relaxed);
  }
  uint8_t* Base() const { return base_; }
  int Fd() const { return fd_; }

 private:
  struct Shard {
    std::mutex mu;
    std::unordered_map<std::string, ObjectEntry> objects;
  };

  Shard& ShardFor(const std::string& key) {
    return shards_[std::hash<std::string>{}(key) % kShards];
  }

  void EraseLocked(Shard& sh,
                   std::unordered_map<std::string, ObjectEntry>::iterator it) {
    alloc_.Free(it->second.offset, it->second.alloc_size);
    used_.fetch_sub(it->second.alloc_size, std::memory_order_relaxed);
    num_objects_.fetch_sub(1, std::memory_order_relaxed);
    sh.objects.erase(it);
  }

  std::string name_;
  uint64_t capacity_;
  int fd_;
  uint8_t* base_;
  Allocator alloc_;
  std::array<Shard, kShards> shards_;
  std::atomic<uint64_t> used_{0};
  std::atomic<uint64_t> num_objects_{0};
  std::atomic<uint64_t> tick_{0};  // LRU clock
};

std::string MakeKey(const uint8_t* key, uint32_t keylen) {
  return std::string(reinterpret_cast<const char*>(key), keylen);
}

}  // namespace

extern "C" {

void* store_open(const char* name, uint64_t capacity) {
  try {
    return new ShmStore(name, capacity);
  } catch (...) {
    return nullptr;
  }
}

void store_close(void* s) { delete static_cast<ShmStore*>(s); }

int64_t store_put(void* s, const uint8_t* key, uint32_t keylen,
                  const uint8_t* data, uint64_t size) {
  return static_cast<ShmStore*>(s)->Put(MakeKey(key, keylen), data, size);
}

int64_t store_create(void* s, const uint8_t* key, uint32_t keylen,
                     uint64_t size) {
  return static_cast<ShmStore*>(s)->Create(MakeKey(key, keylen), size);
}

int store_seal(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Seal(MakeKey(key, keylen));
}

int store_get(void* s, const uint8_t* key, uint32_t keylen, uint64_t* offset,
              uint64_t* size) {
  return static_cast<ShmStore*>(s)->Get(MakeKey(key, keylen), offset, size);
}

int store_delete(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Delete(MakeKey(key, keylen));
}

uint64_t store_used(void* s) { return static_cast<ShmStore*>(s)->Used(); }

uint64_t store_capacity(void* s) {
  return static_cast<ShmStore*>(s)->Capacity();
}

uint64_t store_largest_free(void* s) {
  return static_cast<ShmStore*>(s)->LargestFreeBlock();
}

uint64_t store_num_objects(void* s) {
  return static_cast<ShmStore*>(s)->NumObjects();
}

int store_pin(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Pin(MakeKey(key, keylen));
}

int store_unpin(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Unpin(MakeKey(key, keylen));
}

int store_choose_victims(void* s, uint64_t needed, uint8_t* out,
                         uint32_t out_cap, uint64_t* covered) {
  return static_cast<ShmStore*>(s)->ChooseVictims(needed, out, out_cap,
                                                  covered);
}

}  // extern "C"
