// Shared-memory object store — the native core of the per-node store.
//
// TPU-native equivalent of the reference's Plasma store
// (src/ray/object_manager/plasma/: dlmalloc over mmap'd shm, object table,
// create/seal lifecycle, eviction hooks).  Differences by design:
//   * one flat shm segment with a first-fit free-list allocator
//     (coalescing on free) instead of vendored dlmalloc;
//   * the object table lives in process memory (the store is owned by the
//     node daemon); process-mode worker clients mmap the same segment and
//     receive (offset, size) handles over their RPC channel — zero-copy
//     reads/writes, the plasma client model (plasma/client.cc);
//   * LRU eviction policy (pin counts, victim selection,
//     delete-while-pinned deferred free) is native
//     (eviction_policy.h parity); the spill IO callback stays in the
//     Python LocalObjectManager.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>
#include <algorithm>

namespace {

struct Block {
  uint64_t offset;
  uint64_t size;
};

struct ObjectEntry {
  uint64_t offset;
  uint64_t size;
  bool sealed;
  uint32_t pin_count;
  uint64_t lru_tick;  // global counter value at last touch
  bool deleted;       // delete-while-pinned: freed on last unpin
};

class ShmStore {
 public:
  ShmStore(const char* name, uint64_t capacity)
      : name_(name), capacity_(capacity) {
    fd_ = shm_open(name, O_CREAT | O_RDWR, 0600);
    if (fd_ < 0) throw std::runtime_error("shm_open failed");
    if (ftruncate(fd_, static_cast<off_t>(capacity)) != 0) {
      close(fd_);
      throw std::runtime_error("ftruncate failed");
    }
    base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       fd_, 0));
    if (base_ == MAP_FAILED) {
      close(fd_);
      throw std::runtime_error("mmap failed");
    }
    // One free block spanning the whole segment.
    free_by_offset_[0] = capacity;
  }

  ~ShmStore() {
    munmap(base_, capacity_);
    close(fd_);
    shm_unlink(name_.c_str());
  }

  // Returns offset, -1 on OOM, -2 if already present, -3 if the key is
  // in deleted-pending state (freed on last unpin; not re-usable yet).
  int64_t Put(const std::string& key, const uint8_t* data, uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it != objects_.end()) {
      if (it->second.deleted) return -3;
      if (!it->second.sealed && it->second.pin_count == 0) {
        // Stale create-reservation (client write/seal failed): the
        // bytes were never valid — reclaim and write fresh.
        EraseLocked(it);
      } else {
        return -2;
      }
    }
    int64_t off = Allocate(Align(size));
    if (off < 0) return -1;
    std::memcpy(base_ + off, data, size);
    objects_[key] =
        ObjectEntry{static_cast<uint64_t>(off), size, true, 0, ++tick_,
                    false};
    used_ += Align(size);
    return off;
  }

  // Create without copying (caller writes through the mapped segment,
  // then seals) — the plasma create/seal lifecycle.
  int64_t Create(const std::string& key, uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    auto eit = objects_.find(key);
    if (eit != objects_.end()) return eit->second.deleted ? -3 : -2;
    int64_t off = Allocate(Align(size));
    if (off < 0) return -1;
    objects_[key] =
        ObjectEntry{static_cast<uint64_t>(off), size, false, 0, ++tick_,
                    false};
    used_ += Align(size);
    return off;
  }

  int Seal(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end()) return -1;
    it->second.sealed = true;
    return 0;
  }

  // Returns (offset, size) through out params; -1 if missing/unsealed.
  // Touches the LRU clock (eviction_policy.h parity: reads refresh).
  int Get(const std::string& key, uint64_t* offset, uint64_t* size) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end() || !it->second.sealed ||
        it->second.deleted) {
      return -1;
    }
    it->second.lru_tick = ++tick_;
    *offset = it->second.offset;
    *size = it->second.size;
    return 0;
  }

  int Pin(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end() || it->second.deleted) return -1;
    it->second.pin_count++;
    return 0;
  }

  int Unpin(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end() || it->second.pin_count == 0) return -1;
    it->second.pin_count--;
    if (it->second.pin_count == 0 && it->second.deleted) {
      EraseLocked(it);
    }
    return 0;
  }

  // LRU victim selection (eviction_policy.h ChooseObjectsToEvict
  // parity): pick least-recently-touched sealed+unpinned objects until
  // >= needed bytes are covered (best effort — fewer bytes when little
  // is evictable; the caller inspects covered_out).  Writes
  // [u32 len][key bytes]* into out; returns #victims, or -2 if the
  // out buffer is too small.
  int ChooseVictims(uint64_t needed, uint8_t* out, uint32_t out_cap,
                    uint64_t* covered_out) {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<std::pair<uint64_t, const std::string*>> cand;
    for (auto& kv : objects_) {
      if (kv.second.sealed && kv.second.pin_count == 0 &&
          !kv.second.deleted) {
        cand.emplace_back(kv.second.lru_tick, &kv.first);
      }
    }
    std::sort(cand.begin(), cand.end());
    uint64_t covered = 0;
    uint32_t pos = 0;
    int n = 0;
    for (auto& c : cand) {
      if (covered >= needed) break;
      const std::string& k = *c.second;
      if (pos + 4 + k.size() > out_cap) return -2;
      uint32_t len = static_cast<uint32_t>(k.size());
      std::memcpy(out + pos, &len, 4);
      std::memcpy(out + pos + 4, k.data(), k.size());
      pos += 4 + len;
      covered += Align(objects_[k].size);
      n++;
    }
    *covered_out = covered;
    return n;
  }

  int Delete(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(key);
    if (it == objects_.end()) return -1;
    if (it->second.pin_count > 0) {
      // Deferred free (plasma release semantics): a client still reads
      // through its mapping; hide the object and free on last unpin.
      it->second.deleted = true;
      return 0;
    }
    EraseLocked(it);
    return 0;
  }

  uint64_t Used() const { return used_; }
  uint64_t Capacity() const { return capacity_; }
  uint64_t NumObjects() {
    std::lock_guard<std::mutex> g(mu_);
    return objects_.size();
  }
  uint8_t* Base() const { return base_; }
  int Fd() const { return fd_; }

 private:
  static uint64_t Align(uint64_t n) { return (n + 63) & ~uint64_t(63); }

  void EraseLocked(std::unordered_map<std::string, ObjectEntry>::iterator it) {
    Free(it->second.offset, Align(it->second.size));
    used_ -= Align(it->second.size);
    objects_.erase(it);
  }

  // First-fit over the offset-ordered free map; splits the block.
  int64_t Allocate(uint64_t size) {
    for (auto it = free_by_offset_.begin(); it != free_by_offset_.end();
         ++it) {
      if (it->second >= size) {
        uint64_t off = it->first;
        uint64_t remaining = it->second - size;
        free_by_offset_.erase(it);
        if (remaining > 0) free_by_offset_[off + size] = remaining;
        return static_cast<int64_t>(off);
      }
    }
    return -1;
  }

  // Free with coalescing of adjacent blocks.
  void Free(uint64_t offset, uint64_t size) {
    auto next = free_by_offset_.lower_bound(offset);
    // Merge with next block if adjacent.
    if (next != free_by_offset_.end() && offset + size == next->first) {
      size += next->second;
      next = free_by_offset_.erase(next);
    }
    // Merge with previous block if adjacent.
    if (next != free_by_offset_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        prev->second += size;
        return;
      }
    }
    free_by_offset_[offset] = size;
  }

  std::string name_;
  uint64_t capacity_;
  int fd_;
  uint8_t* base_;
  std::mutex mu_;
  std::unordered_map<std::string, ObjectEntry> objects_;
  std::map<uint64_t, uint64_t> free_by_offset_;  // offset -> size
  uint64_t used_ = 0;
  uint64_t tick_ = 0;  // LRU clock
};

std::string MakeKey(const uint8_t* key, uint32_t keylen) {
  return std::string(reinterpret_cast<const char*>(key), keylen);
}

}  // namespace

extern "C" {

void* store_open(const char* name, uint64_t capacity) {
  try {
    return new ShmStore(name, capacity);
  } catch (...) {
    return nullptr;
  }
}

void store_close(void* s) { delete static_cast<ShmStore*>(s); }

int64_t store_put(void* s, const uint8_t* key, uint32_t keylen,
                  const uint8_t* data, uint64_t size) {
  return static_cast<ShmStore*>(s)->Put(MakeKey(key, keylen), data, size);
}

int64_t store_create(void* s, const uint8_t* key, uint32_t keylen,
                     uint64_t size) {
  return static_cast<ShmStore*>(s)->Create(MakeKey(key, keylen), size);
}

int store_seal(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Seal(MakeKey(key, keylen));
}

int store_get(void* s, const uint8_t* key, uint32_t keylen, uint64_t* offset,
              uint64_t* size) {
  return static_cast<ShmStore*>(s)->Get(MakeKey(key, keylen), offset, size);
}

int store_delete(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Delete(MakeKey(key, keylen));
}

uint64_t store_used(void* s) { return static_cast<ShmStore*>(s)->Used(); }

uint64_t store_capacity(void* s) {
  return static_cast<ShmStore*>(s)->Capacity();
}

uint64_t store_num_objects(void* s) {
  return static_cast<ShmStore*>(s)->NumObjects();
}

int store_pin(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Pin(MakeKey(key, keylen));
}

int store_unpin(void* s, const uint8_t* key, uint32_t keylen) {
  return static_cast<ShmStore*>(s)->Unpin(MakeKey(key, keylen));
}

int store_choose_victims(void* s, uint64_t needed, uint8_t* out,
                         uint32_t out_cap, uint64_t* covered) {
  return static_cast<ShmStore*>(s)->ChooseVictims(needed, out, out_cap,
                                                  covered);
}

}  // extern "C"
