"""ray_tpu — a TPU-native distributed task/actor runtime.

A ground-up re-design of the capabilities of Ray (reference:
klwuibm/ray @ 2.0.0.dev0) for TPU clusters: task/actor programming model
with an ownership-based distributed object store, per-node schedulers with a
batched TPU bin-packing backend, a GCS-style control plane, placement
groups, an autoscaler, collectives over XLA/ICI, and ML libraries built
purely on this public API.

Public surface parity: ``python/ray/__init__.py`` of the reference.
"""

from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.ids import (  # noqa: F401
    ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID)
from ray_tpu._private.object_ref import ObjectRef  # noqa: F401
from ray_tpu._private.worker import (  # noqa: F401
    available_resources, cancel, cluster_resources, get, get_actor,
    get_gpu_ids, get_tpu_ids, init, is_initialized, kill, nodes, put,
    shutdown, timeline, wait)
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401

__version__ = "0.1.0"
__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "get_gpu_ids",
    "get_tpu_ids", "timeline", "ObjectRef", "method", "exceptions",
    "cross_language",
]


def remote(*args, **kwargs):
    """The ``@remote`` decorator (reference worker.py:2221).

    Bare form::

        @ray_tpu.remote
        def f(x): ...

        @ray_tpu.remote
        class A: ...

    With options::

        @ray_tpu.remote(num_cpus=2, num_tpus=1, max_retries=3)
        def f(x): ...
    """
    import inspect

    from ray_tpu.actor import make_actor_class
    from ray_tpu.remote_function import RemoteFunction

    def make(target, options):
        if inspect.isclass(target):
            return make_actor_class(target, options)
        if not callable(target):
            raise TypeError("@remote target must be a function or class")
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and (callable(args[0])):
        return make(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return lambda target: make(target, kwargs)


def method(num_returns: int = 1, **_):
    """Per-method options decorator (reference ray.method)."""

    def decorator(m):
        m.__ray_num_returns__ = num_returns
        return m

    return decorator


# Convenience namespaces mirroring `ray.util` imports.
from ray_tpu import util  # noqa: E402,F401
