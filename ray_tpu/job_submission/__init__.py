"""Job submission: run driver scripts against the cluster with a
tracked lifecycle.

Parity: reference ``dashboard/modules/job/job_manager.py`` (``JobManager``
:274 — ``submit_job`` :390 runs the entrypoint as a supervised child with
its runtime env materialized, status tracked through
PENDING/RUNNING/SUCCEEDED/FAILED/STOPPED, logs captured per job) and
``python/ray/job_submission/`` (the client API + ``JobStatus``/
``JobInfo`` types).

The entrypoint runs as a real OS process on the node hosting the
JobManager (the head), with its runtime env's working_dir as cwd,
env_vars injected, and logs teed to ``<temp>/jobs/<id>/driver.log``.
Job records live in the GCS KV (namespace ``job``) so any process with
cluster access can query them.
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu._private.debug.lock_order import diag_lock

_JOB_NS = b"job"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    TERMINAL = (STOPPED, SUCCEEDED, FAILED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)
    driver_pid: int = 0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "JobInfo":
        return cls(**json.loads(blob))


class JobManager:
    """Supervises driver subprocesses (job_manager.py:274 parity)."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._kv = cluster.gcs.kv
        self._lock = diag_lock("JobManager._lock")
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stopping: set = set()
        self._log_root = os.path.join(get_config().temp_dir, "jobs")

    # ---- records --------------------------------------------------------
    def _save(self, info: JobInfo):
        self._kv.put(info.submission_id.encode(), info.to_json(),
                     namespace=_JOB_NS)

    def get_job_info(self, submission_id: str) -> Optional[JobInfo]:
        blob = self._kv.get(submission_id.encode(), namespace=_JOB_NS)
        return None if blob is None else JobInfo.from_json(blob)

    def get_job_status(self, submission_id: str) -> Optional[str]:
        info = self.get_job_info(submission_id)
        return None if info is None else info.status

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in self._kv.keys(namespace=_JOB_NS):
            blob = self._kv.get(key, namespace=_JOB_NS)
            if blob is not None:
                out.append(JobInfo.from_json(blob))
        return sorted(out, key=lambda j: j.start_time)

    def log_path(self, submission_id: str) -> str:
        return os.path.join(self._log_root, submission_id, "driver.log")

    def get_job_logs(self, submission_id: str) -> str:
        try:
            with open(self.log_path(submission_id), "r",
                      errors="replace") as f:
                return f.read()
        except OSError:
            return ""

    # ---- lifecycle ------------------------------------------------------
    def submit_job(self, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """Start the entrypoint as a supervised child
        (``_exec_entrypoint``, job_manager.py:123 parity)."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        submission_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        if self.get_job_info(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        info = JobInfo(submission_id=submission_id, entrypoint=entrypoint,
                       metadata=metadata or {}, start_time=time.time())
        self._save(info)

        normalized = runtime_env_mod.normalize(runtime_env, self._kv) \
            if runtime_env else None
        ctx = runtime_env_mod.materialize(normalized, self._kv)
        env = ctx.spawn_env()
        env["PYTHONPATH"] = runtime_env_mod.framework_import_root() + \
            os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_JOB_ID"] = submission_id
        env.setdefault("JAX_PLATFORMS", "cpu")

        log_path = self.log_path(submission_id)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        log_f = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                shlex.split(entrypoint), env=env,
                cwd=ctx.cwd or None,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            log_f.close()
            info.status = JobStatus.FAILED
            info.message = f"failed to start entrypoint: {e}"
            info.end_time = time.time()
            self._save(info)
            return submission_id
        with self._lock:
            self._procs[submission_id] = proc
        info.status = JobStatus.RUNNING
        info.driver_pid = proc.pid
        self._save(info)
        threading.Thread(
            target=self._supervise, args=(submission_id, proc, log_f),
            daemon=True, name=f"ray_tpu::job::{submission_id}").start()
        return submission_id

    def _supervise(self, submission_id: str, proc: subprocess.Popen, log_f):
        rc = proc.wait()
        log_f.close()
        with self._lock:
            self._procs.pop(submission_id, None)
            stopped = submission_id in self._stopping
            self._stopping.discard(submission_id)
        info = self.get_job_info(submission_id)
        if info is None:
            return
        info.end_time = time.time()
        if stopped:
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        elif rc == 0:
            info.status = JobStatus.SUCCEEDED
        else:
            info.status = JobStatus.FAILED
            info.message = f"entrypoint exited with code {rc}"
        self._save(info)

    def stop_job(self, submission_id: str, grace_s: float = 3.0) -> bool:
        """SIGTERM, then SIGKILL after the grace period."""
        with self._lock:
            proc = self._procs.get(submission_id)
            if proc is None:
                return False
            self._stopping.add(submission_id)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            proc.terminate()
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return True
            time.sleep(0.05)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            proc.kill()
        return True

    def wait_job(self, submission_id: str,
                 timeout: Optional[float] = None) -> Optional[str]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            if deadline is not None and time.monotonic() >= deadline:
                return status
            time.sleep(0.1)

    def shutdown(self):
        with self._lock:
            ids = list(self._procs)
        for sid in ids:
            self.stop_job(sid)


class JobSubmissionClient:
    """Client against a running head's wire service (the reference's
    REST ``JobSubmissionClient``, over the framed RPC instead of HTTP).

    ``working_dir`` is packaged CLIENT-side and shipped in the submit
    payload, so `submit --working-dir .` works from any machine that can
    reach the head."""

    def __init__(self, address):
        from ray_tpu.rpc import RpcClient
        self._client = RpcClient(tuple(address))

    def submit_job(self, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        payload = {"entrypoint": entrypoint, "submission_id": submission_id,
                   "metadata": metadata, "runtime_env": None,
                   "working_dir_zip": None}
        if runtime_env:
            from ray_tpu._private import runtime_env as runtime_env_mod
            spec = runtime_env_mod.validate(runtime_env)
            wd = spec.get("working_dir")
            if wd and not str(wd).startswith("pkg://"):
                payload["working_dir_zip"] = runtime_env_mod._zip_dir(wd)
                spec = dict(spec)
                spec.pop("working_dir")
            payload["runtime_env"] = spec
        return self._client.call("submit_job", payload, timeout=120.0)

    def get_job_status(self, submission_id: str) -> Optional[str]:
        return self._client.call("job_status", submission_id, timeout=30.0)

    def get_job_info(self, submission_id: str) -> Optional[dict]:
        return self._client.call("job_info", submission_id, timeout=30.0)

    def get_job_logs(self, submission_id: str) -> str:
        return self._client.call("job_logs", submission_id, timeout=60.0)

    def list_jobs(self) -> List[dict]:
        return self._client.call("list_jobs", None, timeout=30.0)

    def stop_job(self, submission_id: str) -> bool:
        return self._client.call("stop_job", submission_id, timeout=30.0)

    def cluster_status(self) -> dict:
        return self._client.call("cluster_status", None, timeout=30.0)

    def memory_summary(self) -> list:
        return self._client.call("memory_summary", None, timeout=30.0)

    def timeline(self, job: Optional[str] = None,
                 critical_path: bool = False) -> list:
        """Merged chrome://tracing dump; ``job`` restricts it to one
        job's spans, ``critical_path`` overlays that job's critical
        path as flow events."""
        payload = None
        if job or critical_path:
            payload = {"job": job, "critical_path": critical_path}
        return self._client.call("timeline_dump", payload, timeout=60.0)

    def profile_job(self, job: Optional[str] = None,
                    top_k: int = 3) -> dict:
        """Critical-path profile of one job (`ray-tpu profile`):
        stage/node/edge wall-clock attribution along the dependency
        chain, from the head's job-graph store."""
        return self._client.call(
            "profile_job", {"job": job, "top_k": top_k}, timeout=60.0)

    def list_state(self, resource: str, filters: Optional[list] = None,
                   limit: Optional[int] = 100, offset: int = 0) -> list:
        """State API rows (`ray-tpu list tasks/actors/objects/nodes`)."""
        return self._client.call(
            "state_list", {"resource": resource, "filters": filters,
                           "limit": limit, "offset": offset},
            timeout=30.0)

    def summarize_tasks(self) -> dict:
        return self._client.call("state_summary", None, timeout=30.0)

    def latency_summary(self) -> dict:
        """Per-stage task-dispatch latency rollup (p50/p99)."""
        return self._client.call("latency_summary", None, timeout=30.0)

    def debug_dump(self, stacks: bool = True, tail: int = 50,
                   timeout: float = 10.0) -> dict:
        """Cluster-wide introspection dump (`ray-tpu doctor`): the
        head's per-process report plus one per node host, with
        internal-loop liveness."""
        return self._client.call(
            "debug_dump",
            {"stacks": stacks, "tail": tail, "timeout": timeout},
            timeout=timeout * 2 + 10.0)

    def close(self):
        self._client.close()
