"""ray-tpu CLI: operate the framework without writing a driver.

Parity: reference ``python/ray/scripts/scripts.py`` (``ray
start/stop/status/submit/...``) + ``dashboard/modules/job/cli.py``
(``ray job submit/logs/stop/list``), collapsed into one argparse tool:

    python -m ray_tpu start --head [--port 7788] [--num-cpus 8]
    python -m ray_tpu start --address 127.0.0.1:7788 --num-cpus 4
    python -m ray_tpu status
    python -m ray_tpu list tasks --filter state=RUNNING
    python -m ray_tpu summary tasks
    python -m ray_tpu latency
    python -m ray_tpu profile [job] [--top-k 3]
    python -m ray_tpu timeline -o trace.json [--job ID --critical-path]
    python -m ray_tpu submit --working-dir . -- python script.py
    python -m ray_tpu jobs
    python -m ray_tpu logs <job-id>
    python -m ray_tpu job-stop <job-id>
    python -m ray_tpu down

The head address is resolved from ``--address``, then the
``RAY_TPU_ADDRESS`` env var, then the head's address file.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Optional, Tuple

from ray_tpu._private.head_main import DEFAULT_ADDRESS_FILE


def _resolve_address(explicit: Optional[str]) -> Tuple[str, int]:
    addr = explicit or os.environ.get("RAY_TPU_ADDRESS")
    if not addr:
        try:
            with open(DEFAULT_ADDRESS_FILE) as f:
                addr = f.read().strip()
        except OSError:
            raise SystemExit(
                "no head address: pass --address, set RAY_TPU_ADDRESS, or "
                "start a head on this machine first "
                "(`python -m ray_tpu start --head`)")
    host, _, port = addr.rpartition(":")
    return host, int(port)


def _client(args):
    from ray_tpu.job_submission import JobSubmissionClient
    return JobSubmissionClient(_resolve_address(args.address))


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------

def cmd_start(args) -> int:
    if args.head:
        cmd = [sys.executable, "-m", "ray_tpu._private.head_main",
               "--port", str(args.port),
               "--resources", args.resources,
               "--address-file", args.address_file]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if args.block:
            return subprocess.call(cmd)
        proc = _spawn_daemon(cmd, "head")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.exists(args.address_file):
                with open(args.address_file) as f:
                    print(f"head started (pid {proc.pid}) at "
                          f"{f.read().strip()}")
                return 0
            if proc.poll() is not None:
                print("head failed to start", file=sys.stderr)
                return 1
            time.sleep(0.1)
        print("timed out waiting for the head address file",
              file=sys.stderr)
        return 1
    # Worker-host node joining an existing head.
    host, port = _resolve_address(args.address)
    resources = json.loads(args.resources)
    resources.setdefault("CPU", args.num_cpus
                         if args.num_cpus is not None else 1)
    if args.num_tpus:
        resources.setdefault("TPU", args.num_tpus)
    cmd = [sys.executable, "-m", "ray_tpu._private.node_host",
           "--head", f"{host}:{port}",
           "--resources", json.dumps(resources),
           "--name", args.name]
    if args.block:
        return subprocess.call(cmd)
    proc = _spawn_daemon(cmd, args.name or "node")
    print(f"worker host started (pid {proc.pid}), joining {host}:{port}")
    return 0


def _spawn_daemon(cmd, tag: str) -> subprocess.Popen:
    """Detach fully: a daemon must not inherit the CLI's stdio pipes —
    an inherited pipe keeps the caller's readers blocked long after the
    CLI exits.  Output goes to a per-daemon log file instead."""
    log_dir = "/tmp/ray_tpu/logs"
    os.makedirs(log_dir, exist_ok=True)
    log_f = open(os.path.join(log_dir, f"{tag}-{int(time.time())}.log"),
                 "ab")
    return subprocess.Popen(cmd, start_new_session=True,
                            stdin=subprocess.DEVNULL,
                            stdout=log_f, stderr=subprocess.STDOUT)


def cmd_status(args) -> int:
    client = _client(args)
    try:
        status = client.cluster_status()
    finally:
        client.close()
    print(f"{'NODE':34} {'STATE':8} RESOURCES")
    for node in status["nodes"]:
        res = " ".join(f"{k}={v:g}"
                       for k, v in sorted(node["resources"].items()))
        name = node["name"] or node["node_id"][:12]
        print(f"{name:34} {node['state']:8} {res}")
    print("\ntotal:    ",
          {k: round(v, 2) for k, v in sorted(status["total"].items())})
    print("available:",
          {k: round(v, 2) for k, v in sorted(status["available"].items())})
    running = [j for j in status["jobs"] if j["status"] == "RUNNING"]
    if running:
        print(f"\n{len(running)} running job(s):",
              ", ".join(j["submission_id"] for j in running))
    return 0


def cmd_submit(args) -> int:
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    for pair in args.env or []:
        key, _, value = pair.partition("=")
        runtime_env.setdefault("env_vars", {})[key] = value
    entrypoint = " ".join(args.entrypoint)
    if not entrypoint:
        raise SystemExit("no entrypoint: ray-tpu submit -- python script.py")
    client = _client(args)
    try:
        job_id = client.submit_job(entrypoint,
                                   runtime_env=runtime_env or None,
                                   submission_id=args.submission_id)
        print(f"submitted: {job_id}")
        if not args.wait:
            return 0
        while True:
            status = client.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                break
            time.sleep(0.25)
        sys.stdout.write(client.get_job_logs(job_id))
        print(f"job {job_id}: {status}")
        return 0 if status == "SUCCEEDED" else 1
    finally:
        client.close()


def cmd_jobs(args) -> int:
    client = _client(args)
    try:
        jobs = client.list_jobs()
    finally:
        client.close()
    print(f"{'JOB':26} {'STATUS':10} ENTRYPOINT")
    for job in jobs:
        print(f"{job['submission_id']:26} {job['status']:10} "
              f"{job['entrypoint']}")
    return 0


def cmd_logs(args) -> int:
    client = _client(args)
    try:
        sys.stdout.write(client.get_job_logs(args.job_id))
    finally:
        client.close()
    return 0


def cmd_job_stop(args) -> int:
    client = _client(args)
    try:
        ok = client.stop_job(args.job_id)
    finally:
        client.close()
    print("stopped" if ok else "not running")
    return 0


def cmd_memory(args) -> int:
    """Per-node object store summary (reference `ray memory`)."""
    client = _client(args)
    try:
        rows = client.memory_summary()
    finally:
        client.close()
    print(f"{'NODE':18} {'OBJECTS':>8} {'USED':>12} {'CAPACITY':>12} "
          f"{'SPILLED':>10} {'RESTORED':>9} {'EVICTED':>8} "
          f"{'QUEUED':>7} {'QWAIT_MS':>9} "
          f"{'OUT_SESS':>8} {'ADM_Q':>6} {'RELAY_MB':>9}")
    for r in rows:
        stats = r.get("stats", {})
        print(f"{r['node']:18} {r['num_objects']:>8} "
              f"{r['used_bytes']:>12} {r['capacity_bytes']:>12} "
              f"{stats.get('spilled_objects', 0):>10} "
              f"{stats.get('restored_objects', 0):>9} "
              f"{stats.get('evicted_objects', 0):>8} "
              f"{stats.get('queued_creates', 0):>7} "
              f"{stats.get('create_queue_wait_ms', 0.0):>9.1f} "
              f"{stats.get('outbound_sessions_active', 0):>8} "
              f"{stats.get('transfer_admission_queue_depth', 0):>6} "
              f"{stats.get('relay_served_bytes', 0) / 2**20:>9.1f}")
    return 0


def cmd_list(args) -> int:
    """State API listing (reference `ray list tasks/actors/objects`)."""
    filters = []
    for f in args.filter or []:
        if "!=" in f:
            key, _, value = f.partition("!=")
            filters.append((key, "!=", value))
        elif "=" in f:
            key, _, value = f.partition("=")
            filters.append((key, "=", value))
        else:
            raise SystemExit(f"bad --filter {f!r}: expected key=value or "
                             "key!=value")
    client = _client(args)
    try:
        rows = client.list_state(args.resource, filters or None,
                                 limit=args.limit, offset=args.offset)
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(rows, default=str, indent=2))
        return 0
    if getattr(args, "summary", False) and args.resource == "nodes":
        by_state = {}
        fenced_total = 0
        offenders = []
        for row in rows:
            state = row.get("state", "?")
            by_state[state] = by_state.get(state, 0) + 1
            fenced = int(row.get("fenced_rejections", 0) or 0)
            fenced_total += fenced
            if fenced:
                offenders.append((fenced, row.get("node_id", "")[:16]))
        states = " ".join(f"{s}={n}" for s, n in sorted(by_state.items()))
        print(f"{len(rows)} nodes ({states or 'none'})  "
              f"fenced_rejections={fenced_total}")
        offenders.sort(reverse=True)
        for fenced, nid in offenders[:5]:
            print(f"  {nid}: fenced_rejections={fenced}")
        if len(offenders) > 5:
            print(f"  ... and {len(offenders) - 5} more")
        return 0
    columns = {
        "tasks": ("task_id", "name", "state", "attempt", "node_id",
                  "duration_s"),
        "actors": ("actor_id", "state", "name"),
        "objects": ("object_id", "node_id", "size_bytes", "sealed",
                    "pin_count", "spilled"),
        "nodes": ("node_id", "node_name", "state", "incarnation",
                  "fenced_rejections"),
    }[args.resource]
    print(" ".join(f"{c.upper():20}" for c in columns))
    for row in rows:
        cells = []
        for c in columns:
            v = row.get(c, "")
            if c.endswith("_id") and isinstance(v, str):
                v = v[:16]
            elif isinstance(v, float):
                v = f"{v:.4f}"
            cells.append(f"{str(v):20}")
        print(" ".join(cells))
    print(f"\n{len(rows)} row(s)")
    return 0


def cmd_summary(args) -> int:
    """Per-function task rollup (reference `ray summary tasks`)."""
    client = _client(args)
    try:
        summary = client.summarize_tasks()
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(summary, default=str, indent=2))
        return 0
    print(f"{'FUNCTION':32} {'COUNT':>6} {'MEAN_S':>8} STATES")
    for name, row in sorted(summary.get("summary", {}).items()):
        mean = row.get("mean_duration_s")
        mean_s = f"{mean:.4f}" if mean is not None else "-"
        states = " ".join(f"{s}={n}"
                          for s, n in sorted(row["by_state"].items()))
        print(f"{name:32} {row['count']:>6} {mean_s:>8} {states}")
    print(f"\ntracked: {summary.get('total_tasks', 0)}  "
          f"dropped_at_source: {summary.get('dropped_at_source', 0)}  "
          f"evicted_records: {summary.get('evicted_records', 0)}")
    return 0


def cmd_latency(args) -> int:
    """Task-dispatch latency decomposition (the BASELINE.json
    north-star p99, split by lifecycle stage)."""
    client = _client(args)
    try:
        stages = client.latency_summary()
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(stages, default=str, indent=2))
        return 0
    order = ("queue_wait", "dispatch", "startup", "total", "execution")
    print(f"{'STAGE':12} {'COUNT':>7} {'MEAN_MS':>9} {'P50_MS':>9} "
          f"{'P99_MS':>9} {'MAX_MS':>9}")
    for stage in sorted(stages, key=lambda s: (order.index(s)
                                               if s in order else 99, s)):
        row = stages[stage]
        print(f"{stage:12} {row['count']:>7} "
              f"{row['mean_s'] * 1000:>9.3f} "
              f"{row['p50_s'] * 1000:>9.3f} "
              f"{row['p99_s'] * 1000:>9.3f} "
              f"{row['max_s'] * 1000:>9.3f}")
    if not stages:
        print("\n(no finished tasks recorded yet)")
    return 0


def _render_process_report(label: str, report: dict,
                           recorder_tail: int) -> None:
    """Doctor rendering for ONE OS process's debug report: stalled
    loops first, then hottest locks, deepest queues, swallowed
    exceptions and the flight-recorder tail."""
    if report.get("error"):
        print(f"\n== {label}: UNREACHABLE ({report['error']})")
        wedge = report.get("last_wedge_report")
        if wedge:
            print(f"  last wedge (head-held evidence): loop "
                  f"{wedge.get('loop')} handler {wedge.get('handler')} "
                  f"stalled {wedge.get('stalled_for_s')}s")
        return
    print(f"\n== {label} (pid {report.get('pid')}, stall budget "
          f"{report.get('stall_budget_s')}s)")
    loops = report.get("loops", [])
    wedged = [lp for lp in loops if lp.get("wedged")]
    for lp in loops:
        mark = "WEDGED" if lp.get("wedged") else "ok"
        busy = (f"busy {lp['busy_for_s']:.2f}s in "
                f"{lp.get('handler') or '?'}"
                if lp.get("busy_for_s") else
                f"idle {lp.get('idle_for_s', 0):.2f}s")
        print(f"  loop {lp['name']:<32} [{mark:6}] {busy}  "
              f"queue={lp.get('queue_depth', 0)} "
              f"lag_max={lp.get('lag_max_s', 0):.4f}s "
              f"slowest={lp.get('slowest_handler', '')}"
              f"({lp.get('slowest_handler_s', 0):.4f}s)")
    for wr in report.get("wedges", []):
        print(f"  wedge: loop {wr.get('loop')} handler "
              f"{wr.get('handler')} stalled {wr.get('stalled_for_s')}s "
              f"(crash file: {wr.get('crash_file', '-')})")
        stacks = wr.get("stacks") or {}
        loop_name = wr.get("loop", "") or ""
        hit = next((t for t in stacks if loop_name and loop_name in t),
                   next(iter(stacks), None))
        if hit is not None:
            print(f"    stack of {hit}:")
            for ln in stacks[hit][-8:]:
                print(f"      {ln}")
    locks = report.get("locks", [])
    if locks:
        print("  hottest locks (by total sampled acquire-wait):")
        for lk in locks[:5]:
            print(f"    {lk['lock']:<40} acquires={lk['acquires']} "
                  f"contended={lk['contended']} "
                  f"wait_total={lk['wait_total_s']:.4f}s "
                  f"wait_max={lk['wait_max_s']:.4f}s "
                  f"hold_max={lk['hold_max_s']:.4f}s")
    held = report.get("held_locks") or {}
    for tname, rows in held.items():
        print(f"  held locks [{tname}]: " + "; ".join(rows))
    swallowed = report.get("swallowed") or {}
    if swallowed:
        tops = sorted(swallowed.items(), key=lambda kv: -kv[1])[:5]
        print("  swallowed exceptions: " +
              ", ".join(f"{site}={n}" for site, n in tops))
    rec = report.get("recorder_tail") or []
    stats = report.get("recorder_stats") or {}
    if rec:
        print(f"  flight recorder (last {min(len(rec), recorder_tail)} "
              f"of {stats.get('written', '?')} recorded, "
              f"{stats.get('dropped', 0)} dropped):")
        for row in rec[-recorder_tail:]:
            extra = {k: v for k, v in row.items()
                     if k not in ("ts", "cat")}
            print(f"    {row.get('ts', 0):.3f} {row.get('cat'):<24} "
                  + " ".join(f"{k}={v}" for k, v in extra.items()))


def cmd_doctor(args) -> int:
    """Cluster-wide "why is it stuck" report: stalled loops, hottest
    locks, deepest queues and the last-N flight-recorder events from
    every OS process, plus per-node internal-loop liveness."""
    client = _client(args)
    try:
        dump = client.debug_dump(stacks=True, tail=args.tail)
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(dump, default=str, indent=2))
        return 0
    liveness = dump.get("liveness") or {}
    membership = dump.get("membership") or {}
    if args.summary:
        _render_doctor_summary(dump, liveness, membership)
        return 0
    degraded = sorted(n for n, st in liveness.items()
                      if st.get("degraded"))
    print(f"nodes: {len(dump.get('nodes', {}))} remote + head; "
          f"internal-loop liveness degraded: "
          f"{', '.join(degraded) if degraded else 'none'}")
    for node, st in sorted(liveness.items()):
        print(f"  {node}: {'DEGRADED' if st.get('degraded') else 'ok'} "
              f"(wedges={st.get('wedges', 0)})")
    if membership:
        # Paginate: at 64 nodes the full roster drowns the report.
        rows = sorted(membership.items())
        shown = rows[:max(0, args.max_nodes)] \
            if args.max_nodes > 0 else rows
        print("membership (heartbeat plane):")
        for node, st in shown:
            fenced = st.get("fenced_rejections", 0)
            extra = ""
            if fenced:
                by_verb = st.get("fenced_by_verb") or {}
                detail = " ".join(f"{v}={n}"
                                  for v, n in sorted(by_verb.items()))
                extra = f" fenced_rejections={fenced} ({detail})"
            print(f"  {node}: {st.get('state'):8} "
                  f"incarnation={st.get('incarnation', 0)}{extra}")
        if len(rows) > len(shown):
            print(f"  ... and {len(rows) - len(shown)} more "
                  f"(--max-nodes to widen, --summary for the rollup)")
    _render_process_report("head", dump.get("head") or {}, args.tail)
    node_reports = sorted((dump.get("nodes") or {}).items())
    shown_reports = node_reports[:max(0, args.max_nodes)] \
        if args.max_nodes > 0 else node_reports
    for node_hex, report in shown_reports:
        _render_process_report(f"node {node_hex}", report or {},
                               args.tail)
    if len(node_reports) > len(shown_reports):
        print(f"\n... and {len(node_reports) - len(shown_reports)} more "
              f"node reports (--max-nodes to widen)")
    return 0


def _render_doctor_summary(dump, liveness, membership) -> None:
    """64-node rollup: counts by state, fenced totals, top-5 offenders
    — the at-a-glance shape of the fleet instead of 64 full rows."""
    by_state = {}
    fenced_total = 0
    offenders = []           # (score, node, detail)
    for node, st in membership.items():
        state = st.get("state", "?")
        by_state[state] = by_state.get(state, 0) + 1
        fenced = st.get("fenced_rejections", 0)
        fenced_total += fenced
        wedges = (liveness.get(node) or {}).get("wedges", 0)
        degraded = bool((liveness.get(node) or {}).get("degraded"))
        score = fenced + 10 * wedges + (100 if degraded else 0)
        if score:
            offenders.append((score, node,
                              f"fenced={fenced} wedges={wedges}"
                              + (" DEGRADED" if degraded else "")))
    degraded_n = sum(1 for st in liveness.values() if st.get("degraded"))
    states = " ".join(f"{s}={n}" for s, n in sorted(by_state.items()))
    print(f"fleet: {len(membership)} nodes ({states or 'none'})  "
          f"fenced_rejections={fenced_total}  "
          f"degraded_loops={degraded_n}")
    offenders.sort(reverse=True)
    if offenders:
        print("top offenders:")
        for _score, node, detail in offenders[:5]:
            print(f"  {node}: {detail}")
        if len(offenders) > 5:
            print(f"  ... and {len(offenders) - 5} more")
    else:
        print("top offenders: none")


def cmd_stacks(args) -> int:
    """Every thread's current stack in every cluster OS process
    (the ad-hoc thread dump PR 6/7 hand-rolled, as a verb)."""
    client = _client(args)
    try:
        dump = client.debug_dump(stacks=True, tail=0)
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(dump, default=str, indent=2))
        return 0

    def render(label, report):
        if report.get("error"):
            print(f"\n== {label}: UNREACHABLE ({report['error']})")
            return
        print(f"\n== {label} (pid {report.get('pid')})")
        for tname, frames in (report.get("stacks") or {}).items():
            print(f"  thread {tname}:")
            for ln in frames:
                print(f"    {ln}")
        for tname, rows in (report.get("held_locks") or {}).items():
            print(f"  held locks [{tname}]: " + "; ".join(rows))

    render("head", dump.get("head") or {})
    for node_hex, report in sorted((dump.get("nodes") or {}).items()):
        render(f"node {node_hex}", report or {})
    return 0


def cmd_timeline(args) -> int:
    """Dump the head's tracing timeline as chrome://tracing JSON
    (reference `ray timeline`); --job restricts the dump to one job's
    spans, --critical-path overlays that job's bottleneck chain as
    flow events."""
    import json as json_mod
    if args.critical_path and not args.job:
        raise SystemExit("--critical-path needs --job <id>: the overlay "
                         "traces ONE job's bottleneck chain")
    client = _client(args)
    try:
        events = client.timeline(job=args.job,
                                 critical_path=args.critical_path)
    finally:
        client.close()
    with open(args.output, "w") as f:
        json_mod.dump(events, f)
    scope = f" (job {args.job})" if args.job else ""
    print(f"wrote {len(events)} events{scope} to {args.output} "
          "(open in chrome://tracing or Perfetto)")
    return 0


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _render_profile(profile: dict) -> None:
    """Human rendering of a job profile: headline attribution, then the
    critical path root -> sink with per-entry stage splits and the
    object edges (producer, bytes, transfer time) between them."""
    if profile.get("error"):
        print(f"profile error: {profile['error']}")
        known = profile.get("known_jobs")
        if known:
            print("known jobs: " + " ".join(j[:16] for j in known))
        return
    cov = profile.get("coverage", {})
    print(f"job {profile.get('job_id', '?')[:16]}  "
          f"wall-clock {profile.get('wall_clock_s', 0):.3f}s  "
          f"critical path {profile.get('path_s', 0):.3f}s over "
          f"{cov.get('path_len', 0)} task(s)  "
          f"[{cov.get('finished', 0)}/{cov.get('tasks', 0)} finished"
          + (f", {cov['unfinished_tasks']} still running"
             if cov.get("unfinished_tasks") else "") + "]")
    print(f"bottleneck: {profile.get('headline', '')}")
    attribution = profile.get("attribution", {})
    by_stage = attribution.get("by_stage", {})
    if by_stage:
        print(f"\n{'STAGE':12} {'SECONDS':>10} {'SHARE':>7}")
        for stage, row in sorted(by_stage.items(),
                                 key=lambda kv: -kv[1]["seconds"]):
            print(f"{stage:12} {row['seconds']:>10.4f} "
                  f"{100.0 * row['fraction']:>6.1f}%")
    by_node = attribution.get("by_node", {})
    if by_node:
        print(f"\n{'NODE':14} {'SECONDS':>10} {'SHARE':>7}")
        for node, row in sorted(by_node.items(),
                                key=lambda kv: -kv[1]["seconds"]):
            print(f"{(node or '?')[:12]:14} {row['seconds']:>10.4f} "
                  f"{100.0 * row['fraction']:>6.1f}%")
    print("\nCRITICAL PATH (root -> sink):")
    for entry in profile.get("path", []):
        edge = entry.get("edge")
        if edge:
            detail = f"arg {edge['object_id'][:12]} from " \
                     f"{edge['producer'] or edge['producer_task_id'][:12]}"
            if edge.get("bytes"):
                detail += f" {_fmt_bytes(edge['bytes'])}"
            if edge.get("transfer_s"):
                detail += f" transfer {edge['transfer_s']:.4f}s"
            if edge.get("restore_s"):
                detail += f" restore {edge['restore_s']:.4f}s"
            if edge.get("spill_s"):
                detail += f" spill {edge['spill_s']:.4f}s"
            print(f"    |  ({detail})")
        stages = " ".join(f"{k}={v:.4f}s"
                          for k, v in sorted(
                              entry["stages"].items(),
                              key=lambda kv: -kv[1]))
        print(f"  {entry['name'] or entry['task_id'][:12]:32} "
              f"[{(entry['node_id'] or '?')[:12]}] "
              f"window {entry['window_s']:.4f}s: {stages}")
    near = profile.get("near_critical", [])
    if near:
        print("\nnear-critical (smallest slack first):")
        for row in near:
            print(f"  at {row['at_task']}: {row['candidate']} finished "
                  f"{row['slack_s']:.4f}s before {row['instead_of']}")


def cmd_profile(args) -> int:
    """Causal job profile (`ray-tpu profile <job>`): the critical path
    of the job's task DAG with per-stage/per-node/per-edge wall-clock
    attribution — "why did this job take 30s", answered along the
    dependency chain."""
    client = _client(args)
    try:
        profile = client.profile_job(args.job, top_k=args.top_k)
    finally:
        client.close()
    if args.output == "json":
        print(json.dumps(profile, default=str, indent=2))
    else:
        _render_profile(profile)
    return 1 if profile.get("error") else 0


def cmd_up(args) -> int:
    """Launch a local cluster from a YAML/JSON config: one head + N
    worker-host processes (reference `ray up` with the local/fake
    provider collapsed in — no SSH in this image; multi-host uses
    `start --address` on each machine)."""
    import json as json_mod
    with open(args.cluster_config) as f:
        text = f.read()
    try:
        cfg = json_mod.loads(text)
    except json_mod.JSONDecodeError:
        cfg = _parse_simple_yaml(text)
    head_cfg = cfg.get("head", {})
    cmd = [sys.executable, "-m", "ray_tpu._private.head_main",
           "--address-file", args.address_file]
    if head_cfg.get("num_cpus") is not None:
        cmd += ["--num-cpus", str(head_cfg["num_cpus"])]
    if head_cfg.get("port"):
        cmd += ["--port", str(head_cfg["port"])]
    _spawn_daemon(cmd, "head")
    address = _wait_for_address_file(args.address_file)
    print(f"head up at {address}")
    for worker in cfg.get("workers", []):
        count = int(worker.get("count", 1))
        for _ in range(count):
            wcmd = [sys.executable, "-m",
                    "ray_tpu._private.node_host",
                    "--head", address,
                    "--resources",
                    json_mod.dumps(worker.get("resources", {})),
                    "--name", worker.get("name", "")]
            _spawn_daemon(wcmd, "node")
    n = sum(int(w.get("count", 1)) for w in cfg.get("workers", []))
    print(f"launched {n} worker-host node(s); "
          f"`ray-tpu status --address {address}` to inspect, "
          f"`ray-tpu down` to stop")
    return 0


def _parse_simple_yaml(text: str) -> dict:
    """Minimal YAML subset (maps, lists of maps, scalars) so cluster
    configs read naturally without a yaml dependency."""
    import re
    root: dict = {}
    stack = [(-1, root)]
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        indent = len(raw) - len(raw.lstrip())
        line = raw.strip()
        while stack and indent <= stack[-1][0]:
            stack.pop()
        container = stack[-1][1]
        if line.startswith("- "):
            item: dict = {}
            if not isinstance(container, list):
                raise ValueError(f"unexpected list item: {raw!r}")
            container.append(item)
            stack.append((indent, item))
            line = line[2:]
            indent += 2
            container = item
        m = re.match(r"([^:]+):\s*(.*)$", line)
        if not m:
            raise ValueError(f"unparseable line: {raw!r}")
        key, value = m.group(1).strip(), m.group(2).strip()
        if not value:
            child: object = [] if key in ("workers",) else {}
            container[key] = child
            stack.append((indent, child))
        else:
            if re.fullmatch(r"-?\d+", value):
                container[key] = int(value)
            elif re.fullmatch(r"-?\d+\.\d*", value):
                container[key] = float(value)
            else:
                container[key] = value.strip("'\"")
    return root


def _wait_for_address_file(path: str, timeout: float = 60.0) -> str:
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            addr = open(path).read().strip()
            if addr:
                return addr
        time.sleep(0.1)
    raise SystemExit(f"head never wrote {path}")


def cmd_down(args) -> int:
    from ray_tpu.rpc import RpcClient
    host, port = _resolve_address(args.address)
    client = RpcClient((host, port))
    try:
        client.call("shutdown_head", None, timeout=10.0)
        print(f"head at {host}:{port} shutting down")
        return 0
    finally:
        client.close()


# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head or a worker-host node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None,
                   help="head to join (worker-host mode)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--name", default="")
    p.add_argument("--address-file", default=DEFAULT_ADDRESS_FILE)
    p.add_argument("--block", action="store_true",
                   help="run in the foreground")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster nodes, resources, jobs")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("submit", help="submit a job: submit -- python x.py")
    p.add_argument("--address", default=None)
    p.add_argument("--working-dir", default=None)
    p.add_argument("--env", action="append", metavar="KEY=VALUE")
    p.add_argument("--submission-id", default=None)
    p.add_argument("--no-wait", dest="wait", action="store_false")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit, wait=True)

    p = sub.add_parser("jobs", help="list jobs")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("logs", help="print a job's driver log")
    p.add_argument("job_id")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("job-stop", help="stop a running job")
    p.add_argument("job_id")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_job_stop)

    p = sub.add_parser("memory", help="per-node object store summary")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("list", help="list cluster state: "
                                    "tasks/actors/objects/nodes")
    p.add_argument("resource",
                   choices=["tasks", "actors", "objects", "nodes"])
    p.add_argument("--filter", action="append", metavar="KEY=VALUE",
                   help="e.g. --filter state=FINISHED (also KEY!=VALUE)")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--offset", type=int, default=0)
    p.add_argument("--summary", action="store_true",
                   help="nodes only: state/fenced rollup + top-5 "
                        "offenders instead of one row per node")
    p.add_argument("--output", choices=["table", "json"], default="table")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary", help="rollups: summary tasks")
    p.add_argument("resource", choices=["tasks"])
    p.add_argument("--output", choices=["table", "json"], default="table")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("latency", help="task-dispatch latency "
                                       "decomposition (p50/p99 by stage)")
    p.add_argument("--output", choices=["table", "json"], default="table")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_latency)

    p = sub.add_parser("doctor", help="why-is-it-stuck report: stalled "
                                      "loops, hottest locks, recorder "
                                      "tails from every process")
    p.add_argument("--output", choices=["table", "json"], default="table")
    p.add_argument("--tail", type=int, default=20,
                   help="flight-recorder events shown per process")
    p.add_argument("--summary", action="store_true",
                   help="one-screen fleet rollup: counts by state, "
                        "fenced totals, top-5 offenders")
    p.add_argument("--max-nodes", type=int, default=16,
                   help="membership/report rows shown before "
                        "pagination (0 = unlimited)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser("stacks", help="all thread stacks in every "
                                      "cluster OS process")
    p.add_argument("--output", choices=["table", "json"], default="table")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_stacks)

    p = sub.add_parser("timeline", help="dump chrome://tracing JSON")
    p.add_argument("--address", default=None)
    p.add_argument("-o", "--output", default="timeline.json")
    p.add_argument("--job", default=None,
                   help="restrict the dump to one job's spans "
                        "(job id hex or unique prefix)")
    p.add_argument("--critical-path", action="store_true",
                   help="overlay the job's critical path as flow "
                        "events (requires --job)")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("profile", help="critical-path profile of a "
                                       "job: stage/node/edge "
                                       "wall-clock attribution")
    p.add_argument("job", nargs="?", default=None,
                   help="job id hex or unique prefix (default: the "
                        "most recently updated job)")
    p.add_argument("--top-k", type=int, default=3,
                   help="near-critical alternatives reported")
    p.add_argument("--output", choices=["table", "json"],
                   default="table")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("up", help="launch a local cluster from a "
                                  "YAML/JSON config")
    p.add_argument("cluster_config")
    p.add_argument("--address-file", default=DEFAULT_ADDRESS_FILE)
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="shut the head down")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser(
        "envelope",
        help="cluster-scale envelope / chaos soak: stand up a fleet "
             "of node-host processes, drive actors + PGs + relay "
             "broadcasts under a seeded fault schedule",
        add_help=False)
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="forwarded to the envelope driver "
                        "(see `ray-tpu envelope --help`)")
    p.set_defaults(fn=cmd_envelope)
    return parser


def cmd_envelope(args) -> int:
    """Delegate to the envelope driver's own argparse (it owns its many
    knobs); ``main()`` normally short-circuits before parsing, so this
    only fires for programmatic build_parser() callers."""
    from ray_tpu._private.envelope import main as envelope_main
    rest = list(args.rest or [])
    if rest and rest[0] == "--":
        rest = rest[1:]
    return envelope_main(rest)


def main(argv=None) -> int:
    import sys as _sys
    argv = list(_sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "envelope":
        # The envelope driver owns its (many) flags: forward everything
        # verbatim — argparse REMAINDER can't start with an optional.
        from ray_tpu._private.envelope import main as envelope_main
        rest = argv[1:]
        if rest and rest[0] == "--":
            rest = rest[1:]
        return envelope_main(rest)
    args = build_parser().parse_args(argv)
    entry = list(getattr(args, "entrypoint", []) or [])
    if entry and entry[0] == "--":
        args.entrypoint = entry[1:]
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
