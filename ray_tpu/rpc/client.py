"""RPC client: one persistent connection, concurrent in-flight calls.

Reference analogue: ``src/ray/rpc/client_call.h`` (``ClientCall`` — each
call carries a tag; replies are matched back on the io context) and the
per-peer client pools (``core_worker_client_pool.h``).  Calls are
correlated by ``msg_id``; a background reader resolves each reply into
its waiting future, so any number of threads can call concurrently over
the one socket.
"""

from __future__ import annotations

import itertools
import socket
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.rpc import wire


class RpcError(Exception):
    """Remote handler raised (payload = remote traceback) or the
    connection failed."""


class RpcClient:
    def __init__(self, address: Tuple[str, int],
                 connect_timeout: float = 10.0):
        self.address = tuple(address)
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()          # guards sock + pending
        self._write_lock = threading.Lock()
        self._sock = None
        self._pending: Dict[int, Future] = {}
        self._ids = itertools.count(1)
        self._closed = False
        self._ever_connected = False
        #: Optional hook fired (on its own thread) when a NEW connection
        #: replaces a lost one — NOT on the first connect.  Peers use it
        #: to reconcile state whose acks may have died with the old
        #: connection (e.g. worker leases granted but never received).
        self.on_reconnect: Optional[Callable[[], None]] = None

    # ---- public --------------------------------------------------------
    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = 60.0) -> Any:
        return self.call_future(method, payload).result(timeout=timeout)

    def call_future(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()
        msg_id = next(self._ids)
        try:
            sock = self._ensure_connected()
            with self._lock:
                self._pending[msg_id] = fut
            wire.send_msg(sock, (msg_id, method, payload),
                          lock=self._write_lock)
        except Exception as e:
            with self._lock:
                self._pending.pop(msg_id, None)
            fut.set_exception(RpcError(f"send to {self.address} failed: {e}"))
        return fut

    def call_async(self, method: str, payload: Any,
                   callback: Callable[[Any, Optional[Exception]], None]):
        fut = self.call_future(method, payload)

        def on_done(f: Future):
            err = f.exception()
            callback(None if err else f.result(), err)

        fut.add_done_callback(on_done)

    def close(self):
        self._closed = True
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            # shutdown() wakes the reader thread blocked in recv (close
            # alone leaves the file description pinned by the syscall).
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def is_connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    # ---- internals -----------------------------------------------------
    def _ensure_connected(self):
        with self._lock:
            if self._closed:
                raise RpcError("client closed")
            if self._sock is not None:
                return self._sock
            sock = wire.connect(self.address, timeout=self._connect_timeout)
            self._sock = sock
            reconnected = self._ever_connected
            self._ever_connected = True
        threading.Thread(target=self._reader_loop, args=(sock,),
                         daemon=True,
                         name=f"ray_tpu::rpc::client::{self.address}").start()
        hook = self.on_reconnect
        if reconnected and hook is not None:
            # Own thread: the hook typically calls back through this
            # client from what may be a latency-sensitive caller.
            threading.Thread(
                target=hook, daemon=True,
                name=f"ray_tpu::rpc::reconnect::{self.address}").start()
        return sock

    def _reader_loop(self, sock):
        try:
            while True:
                msg_id, ok, payload = wire.recv_msg(sock)
                with self._lock:
                    fut = self._pending.pop(msg_id, None)
                if fut is None:
                    continue
                if ok:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RpcError(str(payload)))
        except (wire.ConnectionClosed, OSError, EOFError) as e:
            with self._lock:
                if self._sock is sock:
                    self._sock = None   # reconnect on next call
                pending, self._pending = self._pending, {}
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(
                        RpcError(f"connection to {self.address} lost: {e}"))
        finally:
            try:
                sock.close()
            except OSError:
                pass
