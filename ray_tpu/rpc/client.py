"""RPC client: one persistent connection, concurrent in-flight calls.

Reference analogue: ``src/ray/rpc/client_call.h`` (``ClientCall`` — each
call carries a tag; replies are matched back on the io context) and the
per-peer client pools (``core_worker_client_pool.h``).  Calls are
correlated by ``msg_id``; a background reader resolves each reply into
its waiting future, so any number of threads can call concurrently over
the one socket.

Robustness additions (retryable_grpc_client parity):

* ``rpc.send`` fault point fires before every outbound request (modes
  drop/delay/duplicate/error, scoped per verb/peer) — the wire half of
  the deterministic chaos plane;
* transport failures raise :class:`RpcConnectionError` (a subclass of
  :class:`RpcError`) so callers — and the retry loop below — can tell
  "the wire died" from "the remote handler raised";
* ``call`` transparently retries timeouts and connection losses for
  verbs classified in :mod:`ray_tpu.rpc.verbs`, minting ONE dedup token
  per logical call for non-idempotent verbs so the server's dedup
  window collapses the retries (and any duplicate deliveries) into a
  single side effect.
"""

from __future__ import annotations

import itertools
import socket
import threading
import uuid
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.rpc import verbs as verbs_mod
from ray_tpu.rpc import wire

_fault_hook = None


def _hook(point: str, **ctx):
    """Lazy-bound fault_injection.hook: the rpc package must stay
    importable without dragging the full ray_tpu package in at module
    import (fault_injection imports ray_tpu.exceptions)."""
    global _fault_hook
    if _fault_hook is None:
        from ray_tpu._private import fault_injection
        _fault_hook = fault_injection.hook
    return _fault_hook(point, **ctx)


class RpcError(Exception):
    """Remote handler raised (payload = remote traceback) or the
    connection failed."""


class RpcConnectionError(RpcError):
    """Transport-level failure (send failed, connection lost, injected
    wire fault) — the request may never have reached the handler, so a
    classified verb is safe to retry."""


class RpcClient:
    def __init__(self, address: Tuple[str, int],
                 connect_timeout: float = 10.0):
        self.address = tuple(address)
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()          # guards sock + pending
        self._write_lock = threading.Lock()
        self._sock = None
        self._pending: Dict[int, Future] = {}
        self._ids = itertools.count(1)
        self._closed = False
        self._ever_connected = False
        #: Optional hook fired (on its own thread) when a NEW connection
        #: replaces a lost one — NOT on the first connect.  Peers use it
        #: to reconcile state whose acks may have died with the old
        #: connection (e.g. worker leases granted but never received).
        self.on_reconnect: Optional[Callable[[], None]] = None

    # ---- public --------------------------------------------------------
    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = 60.0,
             retry: Optional[bool] = None) -> Any:
        """Blocking call.  Verbs classified in :mod:`ray_tpu.rpc.verbs`
        are auto-retried with backoff on timeout / connection loss
        (``retry=False`` opts out, ``retry=True`` forces retry for an
        unclassified verb); non-idempotent classified verbs ride a
        dedup token shared across the retries.  A remote handler
        exception is NEVER retried — it is deterministic."""
        retryable = verbs_mod.is_retryable(method) if retry is None \
            else bool(retry)
        if not retryable:
            return self.call_future(method, payload).result(timeout=timeout)
        from ray_tpu._private.config import get_config
        cfg = get_config()
        attempts = max(1, cfg.rpc_retry_attempts)
        backoff = cfg.rpc_retry_backoff_s
        token = uuid.uuid4().bytes if verbs_mod.needs_dedup(method) else None
        last_err: Optional[Exception] = None
        for attempt in range(attempts):
            fut = self.call_future(method, payload, dedup_token=token)
            try:
                return fut.result(timeout=timeout)
            except FutureTimeoutError:
                last_err = RpcConnectionError(
                    f"{method} to {self.address} timed out "
                    f"(attempt {attempt + 1}/{attempts})")
            except RpcConnectionError as e:
                last_err = e
            if self._closed:
                break
            if attempt + 1 < attempts:
                import time
                time.sleep(backoff * (2 ** attempt))
        raise last_err

    def call_future(self, method: str, payload: Any = None,
                    dedup_token: Optional[bytes] = None) -> Future:
        fut: Future = Future()
        msg_id = next(self._ids)
        if dedup_token is None and verbs_mod.needs_dedup(method):
            # Even one-shot sends of a mutating verb carry a token:
            # duplicate DELIVERY (a flaky wire, an armed duplicate
            # fault) must collapse in the server's window exactly like
            # a client retry would.
            dedup_token = uuid.uuid4().bytes
        msg = (msg_id, method, payload) if dedup_token is None \
            else (msg_id, method, payload, dedup_token)
        action = None
        if not verbs_mod.is_control(method):
            try:
                action = _hook("rpc.send", verb=method,
                               peer=f"{self.address[0]}:{self.address[1]}",
                               peer_host=self.address[0],
                               peer_port=self.address[1])
            except Exception as e:
                fut.set_exception(RpcConnectionError(
                    f"send to {self.address} failed: {e}"))
                return fut
        if action == "drop":
            # Simulated partition: the frame never leaves the process.
            # The future stays pending — exactly what a blackholed
            # packet looks like to the caller (timeout, not error).
            return fut
        try:
            sock = self._ensure_connected()
            with self._lock:
                self._pending[msg_id] = fut
            # A future completed by anything OTHER than the reader (a
            # per-attempt timeout, most notably) would leave its entry
            # behind for the connection's whole lifetime — during an
            # inbound-cut partition the retrying lease path would leak
            # one entry per attempt, unboundedly.  Popping is safe: a
            # late reply for a popped id is simply skipped.
            fut.add_done_callback(
                lambda _f, _mid=msg_id: self._discard_pending(_mid))
            wire.send_msg(sock, msg, lock=self._write_lock)
            if action == "duplicate":
                wire.send_msg(sock, msg, lock=self._write_lock)
        except Exception as e:
            with self._lock:
                self._pending.pop(msg_id, None)
            if not fut.done():
                fut.set_exception(RpcConnectionError(
                    f"send to {self.address} failed: {e}"))
        return fut

    def _discard_pending(self, msg_id: int):
        with self._lock:
            self._pending.pop(msg_id, None)

    def call_async(self, method: str, payload: Any,
                   callback: Callable[[Any, Optional[Exception]], None],
                   timeout: Optional[float] = None):
        """Async call.  With ``timeout`` set, each attempt is bounded
        and — for verbs classified retryable — transport failures and
        timeouts re-send under the SAME dedup token with backoff, so a
        partitioned peer's blackholed request cannot strand the caller
        forever: the server's dedup window collapses a late first
        delivery and its retries into one handler run, and a reply the
        first attempt already produced is simply replayed.  Exhausted
        attempts surface :class:`RpcConnectionError` to the callback
        (lease callers convert that to a rejection and re-lease)."""
        if timeout is None:
            fut = self.call_future(method, payload)

            def on_done(f: Future):
                err = f.exception()
                callback(None if err else f.result(), err)

            fut.add_done_callback(on_done)
            return
        from ray_tpu._private.config import get_config
        cfg = get_config()
        retryable = verbs_mod.is_retryable(method)
        attempts = max(1, cfg.rpc_retry_attempts) if retryable else 1
        backoff = cfg.rpc_retry_backoff_s
        token = uuid.uuid4().bytes if verbs_mod.needs_dedup(method) else None
        state = {"done": False}
        state_lock = threading.Lock()

        def finish(result, err):
            with state_lock:
                if state["done"]:
                    return
                state["done"] = True
                timer = state.get("timer")
            if timer is not None:
                timer.cancel()
            callback(result, err)

        def attempt(i: int):
            with state_lock:
                if state["done"]:
                    return
            fut = self.call_future(method, payload, dedup_token=token)

            def on_timeout():
                # Racing the reader's set_result on the same future:
                # losing the race is fine (the reply won), it just must
                # not crash the timer thread.
                try:
                    fut.set_exception(RpcConnectionError(
                        f"{method} to {self.address} timed out "
                        f"(attempt {i + 1}/{attempts})"))
                except Exception:
                    pass

            timer = threading.Timer(timeout, on_timeout)
            timer.daemon = True
            with state_lock:
                if state["done"]:
                    return
                state["timer"] = timer
            timer.start()

            def on_done(f: Future):
                timer.cancel()
                err = f.exception()
                if err is None:
                    finish(f.result(), None)
                    return
                if isinstance(err, RpcConnectionError) and \
                        i + 1 < attempts and not self._closed:
                    retry = threading.Timer(backoff * (2 ** i),
                                            attempt, args=(i + 1,))
                    retry.daemon = True
                    with state_lock:
                        if state["done"]:
                            return
                        state["timer"] = retry
                    retry.start()
                    return
                finish(None, err)

            fut.add_done_callback(on_done)

        attempt(0)

    def close(self):
        self._closed = True
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            # shutdown() wakes the reader thread blocked in recv (close
            # alone leaves the file description pinned by the syscall).
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def is_connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    # ---- internals -----------------------------------------------------
    def _ensure_connected(self):
        with self._lock:
            if self._closed:
                raise RpcError("client closed")
            if self._sock is not None:
                return self._sock
            sock = wire.connect(self.address, timeout=self._connect_timeout)
            self._sock = sock
            reconnected = self._ever_connected
            self._ever_connected = True
        threading.Thread(target=self._reader_loop, args=(sock,),
                         daemon=True,
                         name=f"ray_tpu::rpc::client::{self.address}").start()
        hook = self.on_reconnect
        if reconnected and hook is not None:
            # Own thread: the hook typically calls back through this
            # client from what may be a latency-sensitive caller.
            threading.Thread(
                target=hook, daemon=True,
                name=f"ray_tpu::rpc::reconnect::{self.address}").start()
        return sock

    def _reader_loop(self, sock):
        try:
            while True:
                msg_id, ok, payload = wire.recv_msg(sock)
                with self._lock:
                    fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    # done(): a per-attempt timeout already failed this
                    # future; the late reply (replayed by the server's
                    # dedup window to the retry attempt too) is stale.
                    continue
                # try/except, not check-then-act: a per-attempt timeout
                # can complete the future BETWEEN the done() check and
                # here, and an InvalidStateError escaping this loop
                # would kill the reader thread without failing pending
                # futures or clearing _sock — wedging the client for
                # good over a benign race.
                try:
                    if ok:
                        fut.set_result(payload)
                    else:
                        fut.set_exception(RpcError(str(payload)))
                except Exception as e:
                    from ray_tpu._private.debug import swallow
                    swallow.noted("rpc.reader_stale_reply", e)
        except (wire.ConnectionClosed, OSError, EOFError) as e:
            with self._lock:
                if self._sock is sock:
                    self._sock = None   # reconnect on next call
                pending, self._pending = self._pending, {}
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(RpcConnectionError(
                        f"connection to {self.address} lost: {e}"))
        finally:
            try:
                sock.close()
            except OSError:
                pass
