"""Chunked object transfer over the framed RPC — the object plane's
push/pull internals.

Parity: reference ``src/ray/object_manager/`` — ``PullManager``
(admission-controlled pulls, pull_manager.cc), ``PushManager`` (chunked
sends, push_manager.cc:95), ``ObjectBufferPool`` (chunk assembly).  The
receiver drives the flow: each ``chunk`` request doubles as the ack for
the previous chunk (per-chunk ack + backpressure in one message), a
bounded number of chunk requests is pipelined to hide latency, and the
sender's admission control caps concurrent transfer sessions and bytes
held.

This lifts the single-frame ceiling (``wire.MAX_FRAME``): an object of
any size crosses as ``object_manager_chunk_size`` frames.

Wire surface (register via :func:`serve_chunks` on any RpcServer):

    fetch_meta   {object_id}        -> None | {"inline": bytes}
                                       | {"token", "size", "chunk_size"}
                                       | {"busy": True}
    fetch_chunk  {token, index}     -> bytes
    fetch_close  {token}            -> True
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, Optional

from ray_tpu._private.config import get_config


class _Session:
    __slots__ = ("blob", "created", "last_access")

    def __init__(self, blob: bytes):
        self.blob = blob
        self.created = time.monotonic()
        self.last_access = self.created


class ChunkServer:
    """Sender side: sessions over serialized blobs with admission
    control (PushManager parity)."""

    SESSION_TTL_S = 120.0

    def __init__(self, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8):
        self._get_blob = get_blob
        self._max_sessions = max_sessions
        self._lock = threading.Lock()
        self._sessions: Dict[str, _Session] = {}

    # ---- handlers ------------------------------------------------------
    def handle_meta(self, payload):
        blob = self._get_blob(payload["object_id"])
        if blob is None:
            return None
        chunk = get_config().object_manager_chunk_size
        if len(blob) <= chunk:
            return {"inline": blob}
        with self._lock:
            self._expire_locked()
            if len(self._sessions) >= self._max_sessions:
                # Admission control: receiver backs off and retries
                # (pull_manager.cc bounded active pulls).
                return {"busy": True}
            token = uuid.uuid4().hex
            self._sessions[token] = _Session(blob)
        return {"token": token, "size": len(blob), "chunk_size": chunk}

    def open_session(self, blob: bytes) -> Optional[dict]:
        """Open a transfer session over an ALREADY-materialized blob
        (lets composite handlers avoid fetching the bytes twice);
        returns the meta dict, or None when admission-full."""
        chunk = get_config().object_manager_chunk_size
        with self._lock:
            self._expire_locked()
            if len(self._sessions) >= self._max_sessions:
                return None
            token = uuid.uuid4().hex
            self._sessions[token] = _Session(blob)
        return {"token": token, "size": len(blob), "chunk_size": chunk}

    def handle_chunk(self, payload) -> Optional[bytes]:
        token, index = payload["token"], payload["index"]
        with self._lock:
            session = self._sessions.get(token)
            if session is None:
                return None
            session.last_access = time.monotonic()
            blob = session.blob
        chunk = get_config().object_manager_chunk_size
        start = index * chunk
        return blob[start:start + chunk]

    def handle_close(self, payload) -> bool:
        with self._lock:
            return self._sessions.pop(payload["token"], None) is not None

    def _expire_locked(self):
        now = time.monotonic()
        for token in [t for t, s in self._sessions.items()
                      if now - s.last_access > self.SESSION_TTL_S]:
            del self._sessions[token]


def serve_chunks(server, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8,
                 prefix: str = "fetch") -> ChunkServer:
    """Register the chunk protocol on an RpcServer."""
    cs = ChunkServer(get_blob, max_sessions=max_sessions)
    server.register(f"{prefix}_meta", cs.handle_meta)
    server.register(f"{prefix}_chunk", cs.handle_chunk)
    server.register(f"{prefix}_close", cs.handle_close)
    return cs


def fetch_chunked(client, object_id_bin: bytes,
                  timeout: float = 300.0, prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Receiver side: pull an object of any size as chunk frames.

    Pipelines ``pipeline`` chunk requests to hide round-trip latency;
    each completed request implicitly acks its chunk.  ``busy`` replies
    back off and retry until the deadline (admission control)."""
    deadline = time.monotonic() + timeout
    backoff = 0.02
    while True:
        meta = client.call(f"{prefix}_meta", {"object_id": object_id_bin},
                           timeout=min(60.0, timeout))
        if meta is None:
            return None
        if "inline" in meta:
            return meta["inline"]
        if meta.get("busy"):
            if time.monotonic() >= deadline:
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
            continue
        break
    return fetch_session(client, meta, timeout=timeout, prefix=prefix,
                         pipeline=pipeline)


def fetch_session(client, meta: dict, timeout: float = 300.0,
                  prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Pull an already-opened transfer session to completion."""
    deadline = time.monotonic() + timeout
    token, size, chunk = meta["token"], meta["size"], meta["chunk_size"]
    n_chunks = (size + chunk - 1) // chunk
    out = bytearray(size)
    try:
        next_index = 0
        inflight = {}
        received = 0
        while received < n_chunks:
            while next_index < n_chunks and len(inflight) < pipeline:
                inflight[next_index] = client.call_future(
                    f"{prefix}_chunk", {"token": token,
                                        "index": next_index})
                next_index += 1
            # Wait for the OLDEST in flight (ordered assembly keeps the
            # buffer write sequential and the ack stream dense).
            index = min(inflight)
            fut = inflight.pop(index)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            data = fut.result(timeout=remaining)
            if data is None:
                return None       # session expired sender-side
            start = index * chunk
            out[start:start + len(data)] = data
            received += 1
        return bytes(out)
    finally:
        try:
            client.call_async(f"{prefix}_close", {"token": token},
                              lambda _r, _e: None)
        except Exception:
            pass
