"""Chunked object transfer over the framed RPC — the object plane's
push/pull internals.

Parity: reference ``src/ray/object_manager/`` — ``PullManager``
(admission-controlled pulls, pull_manager.cc), ``PushManager`` (chunked
sends, push_manager.cc:95), ``ObjectBufferPool`` (chunk assembly).  The
receiver drives the flow: each ``chunk`` request doubles as the ack for
the previous chunk (per-chunk ack + backpressure in one message), a
bounded number of chunk requests is pipelined to hide latency, and the
sender's admission control caps concurrent transfer sessions and bytes
held.

This lifts the single-frame ceiling (``wire.MAX_FRAME``): an object of
any size crosses as ``object_manager_chunk_size`` frames.

Wire surface (register via :func:`serve_chunks` on any RpcServer):

    fetch_meta   {object_id}        -> None | {"inline": bytes}
                                       | {"token", "size", "chunk_size"[, "relay"]}
                                       | {"busy": True}
    fetch_chunk  {token, index}     -> bytes | {"pending": True}
    fetch_close  {token}            -> True

Two collective-transfer extensions ride the same surface:

* **relay sessions** (``get_partial`` hook): when no sealed copy
  exists but a transfer of the object is in flight, the sender serves
  the already-assembled prefix of its transfer writer; a chunk past
  the assembly watermark answers ``{"pending": True}`` (the receiver
  re-requests) and an upstream abort answers ``None`` (the receiver
  fails the session and re-selects another source);
* **sender admission** (``ledger``): outbound sessions are charged to
  the store's :class:`~ray_tpu._private.object_store.TransferLedger` —
  a bounded FIFO queue instead of the thrash of N pullers all backing
  off at once; ``busy`` is only returned after the bounded queue wait.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, Optional

from ray_tpu._private.config import get_config

# One process-wide TTL sweeper over every ChunkServer that has admitted
# a PINNED-view session: a receiver that dies without fetch_close would
# otherwise leak its native pin forever (the deferred-free path never
# fires, the block becomes unevictable) — expiry cannot rely on further
# handler traffic arriving.  WeakSet so the sweeper retains nothing;
# one daemon thread for the whole process, however many servers and
# cluster lifecycles come and go.
_sweep_lock = threading.Lock()
_sweep_servers = None   # weakref.WeakSet, created with the thread


def _register_for_sweep(server: "ChunkServer") -> None:
    global _sweep_servers
    import weakref
    with _sweep_lock:
        if _sweep_servers is None:
            _sweep_servers = weakref.WeakSet()

            def sweep():
                while True:
                    time.sleep(ChunkServer.SESSION_TTL_S / 4.0)
                    with _sweep_lock:
                        servers = list(_sweep_servers)
                    for s in servers:
                        with s._lock:
                            s._expire_locked()

            threading.Thread(target=sweep, daemon=True,
                             name="ray_tpu::chunk-session-sweeper"
                             ).start()
        _sweep_servers.add(server)


class _Session:
    __slots__ = ("blob", "created", "last_access", "release", "partial",
                 "nbytes")

    def __init__(self, blob, release=None, partial=None, nbytes=None):
        self.blob = blob              # bytes OR a pinned memoryview
        self.partial = partial        # relay source (in-flight transfer)
        self.nbytes = nbytes if nbytes is not None else len(blob)
        self.created = time.monotonic()
        self.last_access = self.created
        self.release = release        # unpin/ledger callback

    def close(self):
        release, self.release = self.release, None
        self.blob, self.partial = b"", None
        if release is not None:
            try:
                release()
            except Exception:
                pass


class ChunkServer:
    """Sender side: sessions over serialized payloads with admission
    control (PushManager parity).

    A session's payload is either a materialized ``bytes`` blob or —
    via the ``get_source`` hook — a memoryview pinned straight into the
    sender's shm segment, so serving a transfer never flattens the
    object (the sender half of the zero-copy data plane; the pin is
    released on close/expiry)."""

    SESSION_TTL_S = 120.0

    def __init__(self, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8, get_source=None,
                 get_partial=None, ledger=None):
        self._get_blob = get_blob
        self._get_source = get_source   # key -> (buf, release)|None
        self._get_partial = get_partial  # key -> relay source|None
        self._ledger = ledger           # store TransferLedger (admission)
        self._max_sessions = max_sessions
        self._lock = threading.Lock()
        self._sessions: Dict[str, _Session] = {}

    # ---- handlers ------------------------------------------------------
    def handle_meta(self, payload):
        buf, release, partial = None, None, None
        if self._get_source is not None:
            src = self._get_source(payload["object_id"])
            if src is not None:
                buf, release = src
        if buf is None:
            buf = self._get_blob(payload["object_id"])
        if buf is None and self._get_partial is not None:
            # No sealed copy, but a transfer of the object is in
            # flight here: serve its assembled prefix (chunk relay).
            partial = self._get_partial(payload["object_id"])
        if buf is None and partial is None:
            return None
        chunk = get_config().object_manager_chunk_size
        if partial is None and len(buf) <= chunk:
            inline = bytes(buf)
            if release is not None:
                release()
            return {"inline": inline}
        meta = self._admit(buf, release, partial=partial)
        if meta is None and release is not None:
            release()
        return meta if meta is not None else {"busy": True}

    def open_session(self, blob: bytes) -> Optional[dict]:
        """Open a transfer session over an ALREADY-materialized blob
        (lets composite handlers avoid fetching the bytes twice);
        returns the meta dict, or None when admission-full."""
        return self._admit(blob, None)

    def _admit(self, buf, release, partial=None) -> Optional[dict]:
        chunk = get_config().object_manager_chunk_size
        nbytes = partial.nbytes if partial is not None else len(buf)
        if self._ledger is not None:
            # Sender admission rides the store's outbound ledger: a
            # bounded FIFO queue wait, then busy.  NOT under
            # self._lock — other sessions' chunk serving must never
            # stall behind a queued admit.
            if not self._ledger.try_acquire(
                    nbytes,
                    timeout=get_config()
                    .object_transfer_admission_wait_s):
                return None
            released = [False]
            user_release = release

            def release(_user=user_release, _n=nbytes):
                if not released[0]:
                    released[0] = True
                    self._ledger.release(_n)
                if _user is not None:
                    _user()

        with self._lock:
            self._expire_locked()
            if self._ledger is None and \
                    len(self._sessions) >= self._max_sessions:
                # Legacy admission (no ledger attached — worker/client
                # chunk servers): receiver backs off and retries
                # (pull_manager.cc bounded active pulls).
                return None
            token = uuid.uuid4().hex
            self._sessions[token] = _Session(buf, release,
                                             partial=partial,
                                             nbytes=nbytes)
        if release is not None:
            # Sweep covers pinned views AND ledger slots: a receiver
            # that dies without fetch_close must not leak either.
            _register_for_sweep(self)
        meta = {"token": token, "size": nbytes, "chunk_size": chunk}
        if partial is not None:
            meta["relay"] = True
        return meta

    def handle_chunk(self, payload):
        token, index = payload["token"], payload["index"]
        with self._lock:
            session = self._sessions.get(token)
            if session is None:
                return None
            session.last_access = time.monotonic()
            blob = session.blob
            partial = session.partial
            nbytes = session.nbytes
        chunk = get_config().object_manager_chunk_size
        start = index * chunk
        if partial is not None:
            # Relay serving: bounded wait for the assembly watermark to
            # cover this chunk.  "pending" tells the receiver to
            # re-request (the bounded server-side wait paces the loop);
            # None fails the session — the upstream transfer died and
            # the receiver re-selects another source.
            end = min(start + chunk, nbytes)
            try:
                data = partial.read_range(
                    start, end,
                    timeout=get_config().object_transfer_relay_wait_s)
            except TimeoutError:
                return {"pending": True}
            if data is None:
                return None
            if self._ledger is not None:
                self._ledger.note_served(len(data), relay=True)
            return data
        # bytes() also materializes memoryview slices for the wire codec
        # (the per-chunk copy IS the send serialization, not an extra).
        data = bytes(blob[start:start + chunk])
        if self._ledger is not None:
            self._ledger.note_served(len(data))
        return data

    def handle_close(self, payload) -> bool:
        with self._lock:
            session = self._sessions.pop(payload["token"], None)
        if session is None:
            return False
        session.close()
        return True

    def _expire_locked(self):
        now = time.monotonic()
        for token in [t for t, s in self._sessions.items()
                      if now - s.last_access > self.SESSION_TTL_S]:
            self._sessions.pop(token).close()


def serve_chunks(server, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8,
                 prefix: str = "fetch", get_source=None,
                 get_partial=None, ledger=None) -> ChunkServer:
    """Register the chunk protocol on an RpcServer."""
    cs = ChunkServer(get_blob, max_sessions=max_sessions,
                     get_source=get_source, get_partial=get_partial,
                     ledger=ledger)
    server.register(f"{prefix}_meta", cs.handle_meta)
    server.register(f"{prefix}_chunk", cs.handle_chunk)
    server.register(f"{prefix}_close", cs.handle_close)
    return cs


def fetch_chunked(client, object_id_bin: bytes,
                  timeout: float = 300.0, prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Receiver side: pull an object of any size as chunk frames.

    Pipelines ``pipeline`` chunk requests to hide round-trip latency;
    each completed request implicitly acks its chunk.  ``busy`` replies
    back off and retry until the deadline (admission control)."""
    deadline = time.monotonic() + timeout
    backoff = 0.02
    while True:
        meta = client.call(f"{prefix}_meta", {"object_id": object_id_bin},
                           timeout=min(60.0, timeout))
        if meta is None:
            return None
        if "inline" in meta:
            return meta["inline"]
        if meta.get("busy"):
            if time.monotonic() >= deadline:
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
            continue
        break
    return fetch_session(client, meta, timeout=timeout, prefix=prefix,
                         pipeline=pipeline)


def fetch_session(client, meta: dict, timeout: float = 300.0,
                  prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Pull an already-opened transfer session into a fresh buffer."""
    out = bytearray(meta["size"])
    mv = memoryview(out)
    ok = fetch_session_into(client, meta,
                            lambda off, data: _assign(mv, off, data),
                            timeout=timeout, prefix=prefix,
                            pipeline=pipeline)
    mv.release()
    return bytes(out) if ok else None


def _assign(mv: memoryview, off: int, data) -> None:
    mv[off:off + len(data)] = data


def fetch_session_into(client, meta: dict, sink, timeout: float = 300.0,
                       prefix: str = "fetch", pipeline: int = 4,
                       on_chunk=None) -> bool:
    """Pull an already-opened transfer session through a WINDOWED
    pipeline straight into caller-provided memory.

    ``sink(offset, chunk_bytes)`` lands each chunk at its final offset
    — when the caller hands a reserved shm-segment view this is the
    transfer's ONLY copy (no intermediate ``bytearray``).  ``pipeline``
    chunk requests stay in flight to hide round-trip latency; each
    completed request implicitly acks its chunk (the receiver-driven
    flow of push_manager.cc).  ``on_chunk(nbytes, inflight)`` is an
    optional per-chunk metrics hook.  Returns False on timeout or
    sender-side session expiry (partial writes may have landed; the
    caller aborts its reservation)."""
    deadline = time.monotonic() + timeout
    token, size, chunk = meta["token"], meta["size"], meta["chunk_size"]
    n_chunks = (size + chunk - 1) // chunk
    # Relay stall bound (mirrors the in-process leg's 60 s no-progress
    # cap): a stalled-but-alive upstream must fail this session so the
    # receiver re-selects, not camp on it for the whole pull deadline
    # while holding its writer reservation and the sender's slot.
    stall_cap_s = 60.0
    last_progress = time.monotonic()
    try:
        next_index = 0
        inflight = {}
        received = 0
        while received < n_chunks:
            while next_index < n_chunks and len(inflight) < pipeline:
                inflight[next_index] = client.call_future(
                    f"{prefix}_chunk", {"token": token,
                                        "index": next_index})
                next_index += 1
            # Wait for the OLDEST in flight (ordered assembly keeps the
            # sink write sequential and the ack stream dense).
            index = min(inflight)
            fut = inflight.pop(index)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            data = fut.result(timeout=remaining)
            if isinstance(data, dict) and data.get("pending"):
                # Relay source hasn't assembled this chunk yet: the
                # sender already parked the request for its bounded
                # watermark wait (which paces this loop) — re-request
                # the same chunk; ordered assembly waits on it again.
                if time.monotonic() - last_progress > stall_cap_s:
                    return False  # frozen upstream: caller re-selects
                inflight[index] = client.call_future(
                    f"{prefix}_chunk", {"token": token, "index": index})
                continue
            if data is None:
                return False      # session expired sender-side
            sink(index * chunk, data)
            last_progress = time.monotonic()
            received += 1
            if on_chunk is not None:
                on_chunk(len(data), len(inflight))
        return True
    finally:
        try:
            client.call_async(f"{prefix}_close", {"token": token},
                              lambda _r, _e: None)
        except Exception:
            pass
