"""Chunked object transfer over the framed RPC — the object plane's
push/pull internals.

Parity: reference ``src/ray/object_manager/`` — ``PullManager``
(admission-controlled pulls, pull_manager.cc), ``PushManager`` (chunked
sends, push_manager.cc:95), ``ObjectBufferPool`` (chunk assembly).  The
receiver drives the flow: each ``chunk`` request doubles as the ack for
the previous chunk (per-chunk ack + backpressure in one message), a
bounded number of chunk requests is pipelined to hide latency, and the
sender's admission control caps concurrent transfer sessions and bytes
held.

This lifts the single-frame ceiling (``wire.MAX_FRAME``): an object of
any size crosses as ``object_manager_chunk_size`` frames.

Wire surface (register via :func:`serve_chunks` on any RpcServer):

    fetch_meta   {object_id}        -> None | {"inline": bytes}
                                       | {"token", "size", "chunk_size"}
                                       | {"busy": True}
    fetch_chunk  {token, index}     -> bytes
    fetch_close  {token}            -> True
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, Optional

from ray_tpu._private.config import get_config

# One process-wide TTL sweeper over every ChunkServer that has admitted
# a PINNED-view session: a receiver that dies without fetch_close would
# otherwise leak its native pin forever (the deferred-free path never
# fires, the block becomes unevictable) — expiry cannot rely on further
# handler traffic arriving.  WeakSet so the sweeper retains nothing;
# one daemon thread for the whole process, however many servers and
# cluster lifecycles come and go.
_sweep_lock = threading.Lock()
_sweep_servers = None   # weakref.WeakSet, created with the thread


def _register_for_sweep(server: "ChunkServer") -> None:
    global _sweep_servers
    import weakref
    with _sweep_lock:
        if _sweep_servers is None:
            _sweep_servers = weakref.WeakSet()

            def sweep():
                while True:
                    time.sleep(ChunkServer.SESSION_TTL_S / 4.0)
                    with _sweep_lock:
                        servers = list(_sweep_servers)
                    for s in servers:
                        with s._lock:
                            s._expire_locked()

            threading.Thread(target=sweep, daemon=True,
                             name="ray_tpu::chunk-session-sweeper"
                             ).start()
        _sweep_servers.add(server)


class _Session:
    __slots__ = ("blob", "created", "last_access", "release")

    def __init__(self, blob, release=None):
        self.blob = blob              # bytes OR a pinned memoryview
        self.created = time.monotonic()
        self.last_access = self.created
        self.release = release        # unpin callback for view sessions

    def close(self):
        release, self.release, self.blob = self.release, None, b""
        if release is not None:
            try:
                release()
            except Exception:
                pass


class ChunkServer:
    """Sender side: sessions over serialized payloads with admission
    control (PushManager parity).

    A session's payload is either a materialized ``bytes`` blob or —
    via the ``get_source`` hook — a memoryview pinned straight into the
    sender's shm segment, so serving a transfer never flattens the
    object (the sender half of the zero-copy data plane; the pin is
    released on close/expiry)."""

    SESSION_TTL_S = 120.0

    def __init__(self, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8, get_source=None):
        self._get_blob = get_blob
        self._get_source = get_source   # key -> (buf, release)|None
        self._max_sessions = max_sessions
        self._lock = threading.Lock()
        self._sessions: Dict[str, _Session] = {}

    # ---- handlers ------------------------------------------------------
    def handle_meta(self, payload):
        buf, release = None, None
        if self._get_source is not None:
            src = self._get_source(payload["object_id"])
            if src is not None:
                buf, release = src
        if buf is None:
            buf = self._get_blob(payload["object_id"])
        if buf is None:
            return None
        chunk = get_config().object_manager_chunk_size
        nbytes = len(buf)
        if nbytes <= chunk:
            inline = bytes(buf)
            if release is not None:
                release()
            return {"inline": inline}
        meta = self._admit(buf, release)
        if meta is None and release is not None:
            release()
        return meta if meta is not None else {"busy": True}

    def open_session(self, blob: bytes) -> Optional[dict]:
        """Open a transfer session over an ALREADY-materialized blob
        (lets composite handlers avoid fetching the bytes twice);
        returns the meta dict, or None when admission-full."""
        return self._admit(blob, None)

    def _admit(self, buf, release) -> Optional[dict]:
        chunk = get_config().object_manager_chunk_size
        with self._lock:
            self._expire_locked()
            if len(self._sessions) >= self._max_sessions:
                # Admission control: receiver backs off and retries
                # (pull_manager.cc bounded active pulls).
                return None
            token = uuid.uuid4().hex
            self._sessions[token] = _Session(buf, release)
        if release is not None:
            _register_for_sweep(self)
        return {"token": token, "size": len(buf), "chunk_size": chunk}

    def handle_chunk(self, payload) -> Optional[bytes]:
        token, index = payload["token"], payload["index"]
        with self._lock:
            session = self._sessions.get(token)
            if session is None:
                return None
            session.last_access = time.monotonic()
            blob = session.blob
        chunk = get_config().object_manager_chunk_size
        start = index * chunk
        # bytes() also materializes memoryview slices for the wire codec
        # (the per-chunk copy IS the send serialization, not an extra).
        return bytes(blob[start:start + chunk])

    def handle_close(self, payload) -> bool:
        with self._lock:
            session = self._sessions.pop(payload["token"], None)
        if session is None:
            return False
        session.close()
        return True

    def _expire_locked(self):
        now = time.monotonic()
        for token in [t for t, s in self._sessions.items()
                      if now - s.last_access > self.SESSION_TTL_S]:
            self._sessions.pop(token).close()


def serve_chunks(server, get_blob: Callable[[bytes], Optional[bytes]],
                 max_sessions: int = 8,
                 prefix: str = "fetch", get_source=None) -> ChunkServer:
    """Register the chunk protocol on an RpcServer."""
    cs = ChunkServer(get_blob, max_sessions=max_sessions,
                     get_source=get_source)
    server.register(f"{prefix}_meta", cs.handle_meta)
    server.register(f"{prefix}_chunk", cs.handle_chunk)
    server.register(f"{prefix}_close", cs.handle_close)
    return cs


def fetch_chunked(client, object_id_bin: bytes,
                  timeout: float = 300.0, prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Receiver side: pull an object of any size as chunk frames.

    Pipelines ``pipeline`` chunk requests to hide round-trip latency;
    each completed request implicitly acks its chunk.  ``busy`` replies
    back off and retry until the deadline (admission control)."""
    deadline = time.monotonic() + timeout
    backoff = 0.02
    while True:
        meta = client.call(f"{prefix}_meta", {"object_id": object_id_bin},
                           timeout=min(60.0, timeout))
        if meta is None:
            return None
        if "inline" in meta:
            return meta["inline"]
        if meta.get("busy"):
            if time.monotonic() >= deadline:
                return None
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
            continue
        break
    return fetch_session(client, meta, timeout=timeout, prefix=prefix,
                         pipeline=pipeline)


def fetch_session(client, meta: dict, timeout: float = 300.0,
                  prefix: str = "fetch",
                  pipeline: int = 4) -> Optional[bytes]:
    """Pull an already-opened transfer session into a fresh buffer."""
    out = bytearray(meta["size"])
    mv = memoryview(out)
    ok = fetch_session_into(client, meta,
                            lambda off, data: _assign(mv, off, data),
                            timeout=timeout, prefix=prefix,
                            pipeline=pipeline)
    mv.release()
    return bytes(out) if ok else None


def _assign(mv: memoryview, off: int, data) -> None:
    mv[off:off + len(data)] = data


def fetch_session_into(client, meta: dict, sink, timeout: float = 300.0,
                       prefix: str = "fetch", pipeline: int = 4,
                       on_chunk=None) -> bool:
    """Pull an already-opened transfer session through a WINDOWED
    pipeline straight into caller-provided memory.

    ``sink(offset, chunk_bytes)`` lands each chunk at its final offset
    — when the caller hands a reserved shm-segment view this is the
    transfer's ONLY copy (no intermediate ``bytearray``).  ``pipeline``
    chunk requests stay in flight to hide round-trip latency; each
    completed request implicitly acks its chunk (the receiver-driven
    flow of push_manager.cc).  ``on_chunk(nbytes, inflight)`` is an
    optional per-chunk metrics hook.  Returns False on timeout or
    sender-side session expiry (partial writes may have landed; the
    caller aborts its reservation)."""
    deadline = time.monotonic() + timeout
    token, size, chunk = meta["token"], meta["size"], meta["chunk_size"]
    n_chunks = (size + chunk - 1) // chunk
    try:
        next_index = 0
        inflight = {}
        received = 0
        while received < n_chunks:
            while next_index < n_chunks and len(inflight) < pipeline:
                inflight[next_index] = client.call_future(
                    f"{prefix}_chunk", {"token": token,
                                        "index": next_index})
                next_index += 1
            # Wait for the OLDEST in flight (ordered assembly keeps the
            # sink write sequential and the ack stream dense).
            index = min(inflight)
            fut = inflight.pop(index)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            data = fut.result(timeout=remaining)
            if data is None:
                return False      # session expired sender-side
            sink(index * chunk, data)
            received += 1
            if on_chunk is not None:
                on_chunk(len(data), len(inflight))
        return True
    finally:
        try:
            client.call_async(f"{prefix}_close", {"token": token},
                              lambda _r, _e: None)
        except Exception:
            pass
