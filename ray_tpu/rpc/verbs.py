"""Retry classification of the cluster's RPC verbs.

Reference analogue: gRPC method idempotency options +
``src/ray/rpc/retryable_grpc_client`` — the reference marks which
core-worker/raylet RPCs may be transparently retried after a transport
failure.  Here every verb that ``RpcClient.call`` may auto-retry on a
timeout or connection loss is classified explicitly:

* **idempotent** — re-running the handler is a no-op or a pure read;
  retries need no extra machinery (heartbeats, KV/directory reads,
  object fetches).
* **dedup** — the handler MUTATES state (grants a lease, registers an
  actor/location, stores a return) so a blind retry could double the
  side effect.  These verbs are retried under a client-minted dedup
  token: every send of the same logical call carries the same token,
  and the server's bounded dedup window runs the handler once and
  replays the recorded reply to duplicates — whether the duplicate came
  from a client retry or from duplicate DELIVERY on a flaky wire.

Verbs that mutate state but are deliberately NEVER auto-retried live
in ``NO_RETRY_VERBS`` — long-polls like ``wait_object``,
delta-shipping like ``metrics_report`` whose loss handling is
application-level, timing probes like ``clock_probe``, and the whole
driver/worker-link surface whose retries belong to the caller.  The
set exists so graftcheck's R9 pass can tell "consciously exempt" from
"someone added a mutating verb and forgot": every mutating handler's
verb must appear in exactly one of these registries, and every entry
must name a verb that still exists.

``_CONTROL_VERBS`` are additionally exempt from the ``rpc.send`` /
``rpc.recv`` fault points: they are the chaos plane's own control
channel (arming and healing a partition must work THROUGH the
partition).
"""

from __future__ import annotations

from typing import Optional

#: Pure reads / naturally idempotent writes: retry without a token.
IDEMPOTENT_VERBS = frozenset({
    "ping",
    "heartbeat",
    "kv_get",
    "get_locations",
    "get_node_address",
    "get_resource_report",
    "fetch_object",
    "fault_fired",
    "observability_stats",
    # removals / upserts that are no-ops on re-delivery:
    "unregister_node",         # second removal of a node row is a no-op
    "update_resource_usage",   # head's latest-usage broadcast: pure upsert
    "remove_partial_location", # directory row removal, absent row is fine
    "delete_object",           # deleting an absent object is a no-op
    "pubsub_unsubscribe",      # pop of the subscriber entry, idempotent
})

#: Mutating verbs: retried only under a server-side dedup window keyed
#: by a client-minted token (lease grant/return, actor assignment and
#: task pushes — "exactly once" side effects — registration, location
#: rows, inline return storage, the PG 2PC edges).
DEDUP_VERBS = frozenset({
    "register_node",
    "request_worker_lease",
    "request_worker_lease_batch",
    "return_worker",
    "reconcile_leases",
    "push_task",
    "assign_actor",
    "push_actor_task",
    "actor_worker_died",
    "add_location",
    "remove_location",
    "put_inline",
    "prepare_bundle",
    "commit_bundle",
    "cancel_bundle",
})

#: The chaos plane's own control channel: exempt from rpc.send/rpc.recv
#: fault points so a partition can always be healed through it.
CONTROL_VERBS = frozenset({"arm_fault", "disarm_fault", "fault_fired"})

#: Mutating verbs that are DELIBERATELY never auto-retried.  Each entry
#: is a conscious decision, grouped by why the transport must not
#: retry it:
NO_RETRY_VERBS = frozenset({
    # loss-tolerant shipping — the application heals a lost report
    # (delta shippers re-send on the next change / force a full):
    "metrics_report",
    "wedge_report",
    # timing / long-poll surfaces — a retry would skew the measurement
    # or re-enter a parked wait the caller already abandoned:
    "clock_probe",
    "wait_object",
    # supervised same-host worker link — a wedged worker is REPLACED by
    # the pool (watchdog + reaper), not retried into; a blind re-push
    # would double-execute the task:
    "push",
    "stop",
    "register_worker",
    # shm segment control (same supervised link; create/seal/abort are
    # one-shot lease steps whose failure aborts the put):
    "shm_create",
    "shm_locate",
    "shm_release",
    "shm_seal",
    "shm_abort",
    # pubsub: the first subscribe MINTS the subscriber id (a retry
    # would mint a second), and batch delivery is at-least-once with
    # re-publish handled by the publisher itself:
    "pubsub_subscribe",
    "publish_batch",
    # driver/job surface — the client library and CLI own retries and
    # surface failures to the user instead of silently re-submitting:
    "kv_put",
    "submit_task",
    "submit_actor_task",
    "create_actor",
    "kill_actor",
    "put_object",
    "submit_job",
    "stop_job",
})


def needs_dedup(method: str) -> bool:
    return method in DEDUP_VERBS


def is_retryable(method: str) -> bool:
    return method in IDEMPOTENT_VERBS or method in DEDUP_VERBS


def is_control(method: str) -> bool:
    return method in CONTROL_VERBS


def classify(method: str) -> Optional[str]:
    """"idempotent" | "dedup" | None (never auto-retried)."""
    if method in DEDUP_VERBS:
        return "dedup"
    if method in IDEMPOTENT_VERBS:
        return "idempotent"
    return None
