"""Retry classification of the cluster's RPC verbs.

Reference analogue: gRPC method idempotency options +
``src/ray/rpc/retryable_grpc_client`` — the reference marks which
core-worker/raylet RPCs may be transparently retried after a transport
failure.  Here every verb that ``RpcClient.call`` may auto-retry on a
timeout or connection loss is classified explicitly:

* **idempotent** — re-running the handler is a no-op or a pure read;
  retries need no extra machinery (heartbeats, KV/directory reads,
  object fetches).
* **dedup** — the handler MUTATES state (grants a lease, registers an
  actor/location, stores a return) so a blind retry could double the
  side effect.  These verbs are retried under a client-minted dedup
  token: every send of the same logical call carries the same token,
  and the server's bounded dedup window runs the handler once and
  replays the recorded reply to duplicates — whether the duplicate came
  from a client retry or from duplicate DELIVERY on a flaky wire.

Unclassified verbs are never auto-retried (long-polls like
``wait_object``, delta-shipping like ``metrics_report`` whose loss
handling is application-level, timing probes like ``clock_probe``).

``_CONTROL_VERBS`` are additionally exempt from the ``rpc.send`` /
``rpc.recv`` fault points: they are the chaos plane's own control
channel (arming and healing a partition must work THROUGH the
partition).
"""

from __future__ import annotations

from typing import Optional

#: Pure reads / naturally idempotent writes: retry without a token.
IDEMPOTENT_VERBS = frozenset({
    "ping",
    "heartbeat",
    "kv_get",
    "get_locations",
    "get_node_address",
    "get_resource_report",
    "fetch_object",
    "fault_fired",
    "observability_stats",
})

#: Mutating verbs: retried only under a server-side dedup window keyed
#: by a client-minted token (lease grant/return, actor assignment and
#: task pushes — "exactly once" side effects — registration, location
#: rows, inline return storage, the PG 2PC edges).
DEDUP_VERBS = frozenset({
    "register_node",
    "request_worker_lease",
    "request_worker_lease_batch",
    "return_worker",
    "reconcile_leases",
    "push_task",
    "assign_actor",
    "push_actor_task",
    "actor_worker_died",
    "add_location",
    "remove_location",
    "put_inline",
    "prepare_bundle",
    "commit_bundle",
    "cancel_bundle",
})

#: The chaos plane's own control channel: exempt from rpc.send/rpc.recv
#: fault points so a partition can always be healed through it.
CONTROL_VERBS = frozenset({"arm_fault", "disarm_fault", "fault_fired"})


def needs_dedup(method: str) -> bool:
    return method in DEDUP_VERBS


def is_retryable(method: str) -> bool:
    return method in IDEMPOTENT_VERBS or method in DEDUP_VERBS


def is_control(method: str) -> bool:
    return method in CONTROL_VERBS


def classify(method: str) -> Optional[str]:
    """"idempotent" | "dedup" | None (never auto-retried)."""
    if method in DEDUP_VERBS:
        return "dedup"
    if method in IDEMPOTENT_VERBS:
        return "idempotent"
    return None
