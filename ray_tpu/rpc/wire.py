"""Frame + message codec for the TCP transport.

Frame layout (reference analogue: gRPC's length-prefixed messages over
HTTP/2, ``src/ray/rpc``):

    [4 bytes big-endian payload length][payload]

Payload is a pickled tuple:

    request:  (msg_id, method_name, payload_obj)
    response: (msg_id, ok_flag, payload_or_error)

Pickle (protocol 5) is the codec because the payloads are the same
arbitrary Python object graphs the in-process transport passes by
reference (task specs, serialized-object blobs, resource dicts); the
trust model is identical to the reference's, which runs cloudpickle
bytes received over gRPC from cluster peers — the wire is cluster
-internal, never an untrusted boundary.  Large binary blobs
(SerializedObject.to_bytes()) ride as raw ``bytes`` inside the tuple, so
they are copied but not re-encoded.
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Tuple

_LEN = struct.Struct("!I")
# One frame must hold the largest single object transfer; the reference
# chunks at 5 MiB but its pull manager reassembles up to object-store
# capacity.  1 GiB is a sanity bound, not a design limit.  (Bulk object
# data rides the chunked plane — rpc/chunked.py — in 5 MiB frames.)
MAX_FRAME = 1 << 30

# Versioned connection preamble (reference: gRPC protocol negotiation /
# the RayConfig version handshake): every client opens with
# MAGIC+version, and the server rejects a mismatched peer with a clear
# error instead of a pickle explosion mid-stream.
WIRE_MAGIC = b"RTPU"
WIRE_VERSION = 1
_PREAMBLE = struct.Struct("!4sH")


class ConnectionClosed(Exception):
    pass


class WireVersionMismatch(ConnectionClosed):
    pass


def send_preamble(sock: socket.socket) -> None:
    sock.sendall(_PREAMBLE.pack(WIRE_MAGIC, WIRE_VERSION))


def expect_preamble(sock: socket.socket) -> None:
    """Server side: validate the client's opening preamble."""
    raw = _recv_exact(sock, _PREAMBLE.size)
    magic, version = _PREAMBLE.unpack(raw)
    if magic != WIRE_MAGIC:
        raise WireVersionMismatch(
            f"bad wire magic {magic!r} (not a ray_tpu peer?)")
    if version != WIRE_VERSION:
        raise WireVersionMismatch(
            f"wire protocol version mismatch: peer={version} "
            f"local={WIRE_VERSION}")


def send_msg(sock: socket.socket, obj: Any, lock=None) -> None:
    data = pickle.dumps(obj, protocol=5)
    if len(data) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(data)} bytes")
    frame = _LEN.pack(len(data)) + data
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ConnectionClosed(f"oversized frame: {length}")
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    parts = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionClosed("peer closed")
        parts.append(chunk)
        remaining -= len(chunk)
    return b"".join(parts) if len(parts) != 1 else parts[0]


def connect(address: Tuple[str, int], timeout: float = 10.0
            ) -> socket.socket:
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    send_preamble(sock)
    return sock
