"""Wire transport: length-prefixed framed RPC over TCP.

TPU-native equivalent of the reference's gRPC substrate
(``src/ray/rpc/grpc_server.h`` GrpcServer, ``src/ray/rpc/client_call.h``
ClientCall): a small framed protocol carrying the same service surfaces
(NodeManagerService lease protocol, CoreWorkerService PushTask, object
transfer) between OS processes.  The in-process method-call transport
remains the fast path for same-process clusters; this layer slots in
front of the identical ``Raylet``/``GcsServer`` surfaces for real
multi-process / multi-host deployments.
"""

from ray_tpu.rpc.client import RpcClient, RpcConnectionError, RpcError
from ray_tpu.rpc.server import RpcServer

__all__ = ["RpcClient", "RpcServer", "RpcError", "RpcConnectionError"]
