"""Threaded RPC server: the process-boundary front of a service surface.

Reference analogue: ``src/ray/rpc/grpc_server.h`` — a ``GrpcServer``
binds a port and dispatches each inbound call to a registered handler on
an io-context thread.  Here: one acceptor thread, one reader thread per
connection, and each request runs on its own dispatch thread so a
blocking handler (e.g. a worker lease waiting for dependencies) never
stalls pipelined requests on the same connection.

Handlers are ``name -> callable(payload) -> reply``.  A handler may
instead accept ``(payload, reply_cb)`` by registering with
``register_async`` — the reply is sent whenever ``reply_cb(result)``
fires, which maps 1:1 onto the runtime's callback-style surfaces
(``Raylet.request_worker_lease(spec, reply)``).

Robustness additions:

* ``rpc.recv`` fault point fires before every inbound request
  dispatches (modes drop/delay/duplicate/error, scoped per verb/peer)
  — a dropped recv never runs the handler and never replies, exactly
  what a blackholed packet looks like; a duplicated recv dispatches
  the request twice (the dedup window is what must absorb it).
* Requests carrying a client-minted dedup token (4th frame element, see
  ``rpc.verbs``) run through a bounded server-side dedup window: the
  handler runs ONCE per token, duplicates get the recorded reply (or
  park until the first run replies).  This is what makes timeouts of
  mutating verbs safely retryable.
"""

from __future__ import annotations

import socket
import threading
import traceback
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.rpc import verbs as verbs_mod
from ray_tpu.rpc import wire

_fault_hook = None


def _hook(point: str, **ctx):
    """Lazy-bound fault_injection.hook (see rpc/client.py)."""
    global _fault_hook
    if _fault_hook is None:
        from ray_tpu._private import fault_injection
        _fault_hook = fault_injection.hook
    return _fault_hook(point, **ctx)


def _shutdown_close(sock: socket.socket):
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _DedupWindow:
    """Bounded at-most-once window over client-minted request tokens.

    One entry per token: while the first delivery's handler runs the
    entry is PENDING and duplicate deliveries park their repliers on
    it; once the handler replies the entry caches ``(ok, payload)`` and
    later duplicates get the recorded reply immediately.  Bounded FIFO:
    past ``size`` entries the oldest is evicted — a duplicate arriving
    after eviction re-runs the handler, which is why the window must
    comfortably exceed (in-flight requests x retry attempts), not just
    retry depth.
    """

    def __init__(self, size: int):
        self._size = max(8, size)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, dict]" = OrderedDict()
        self.hits = 0      # duplicate deliveries absorbed (tests assert)

    def admit(self, token: bytes, replier: Callable[[bool, Any], None]
              ) -> bool:
        """True -> caller runs the handler (first delivery).  False ->
        duplicate: the recorded reply was sent (or the replier parked
        until the first run completes)."""
        with self._lock:
            entry = self._entries.get(token)
            if entry is None:
                self._entries[token] = {"done": False, "waiters": []}
                if len(self._entries) > self._size:
                    # Evict oldest COMPLETED entries only.  A pending
                    # entry's handler is still running: evicting it
                    # would drop its parked repliers AND let a retry of
                    # the same token re-run the mutating handler
                    # concurrently — the double side effect the window
                    # exists to prevent.  If everything is pending the
                    # window grows past size (bounded by in-flight
                    # requests) rather than break at-most-once.
                    for tok in list(self._entries):
                        if len(self._entries) <= self._size:
                            break
                        if self._entries[tok]["done"]:
                            del self._entries[tok]
                return True
            self.hits += 1
            if not entry["done"]:
                entry["waiters"].append(replier)
                return False
            ok, payload = entry["ok"], entry["payload"]
        replier(ok, payload)
        return False

    def complete(self, token: bytes, ok: bool, payload: Any) -> None:
        with self._lock:
            entry = self._entries.get(token)
            if entry is None or entry["done"]:
                waiters = []
            else:
                entry["done"] = True
                entry["ok"] = ok
                entry["payload"] = payload
                waiters, entry["waiters"] = entry["waiters"], []
        for w in waiters:
            w(ok, payload)


class RpcServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "rpc"):
        self._handlers: Dict[str, Tuple[Callable, bool]] = {}
        self._name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._stopped = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # Bounded dispatch pool (reference grpc_server.h: a fixed io
        # thread pool, not a thread per call).  Overflow takes a
        # dedicated thread instead of queueing: many handlers block on
        # other requests to this same server (lease dep-waits, gets),
        # so queueing behind them could deadlock.  Daemon threads: a
        # handler stuck in a long wait must not hang interpreter exit.
        from ray_tpu._private.config import get_config
        from ray_tpu._private.daemon_pool import DaemonPool
        cfg = get_config()
        self._pool_size = cfg.rpc_dispatch_pool_size
        self._pool = DaemonPool(self._pool_size,
                                name=f"ray_tpu::rpc::{name}::pool")
        self.dedup_window = _DedupWindow(cfg.rpc_dedup_window_size)
        self._active = 0
        self._active_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"ray_tpu::rpc::{name}::accept")
        self._accept_thread.start()

    # ---- registry ------------------------------------------------------
    def register(self, method: str, handler: Callable[[Any], Any]):
        """Sync handler: return value becomes the reply."""
        self._handlers[method] = (handler, False)

    def register_async(self, method: str,
                       handler: Callable[[Any, Callable], None]):
        """Callback handler: handler(payload, reply_cb); the reply is sent
        when reply_cb(result) is invoked (once)."""
        self._handlers[method] = (handler, True)

    def register_instance(self, obj, methods):
        """Expose the listed bound methods of ``obj`` as sync handlers."""
        for m in methods:
            self.register(m, getattr(obj, m))

    # ---- lifecycle -----------------------------------------------------
    def stop(self):
        self._stopped.set()
        self._pool.stop()
        # shutdown() before close(): a close alone does not tear the
        # connection down while another thread is blocked in recv on the
        # same fd (the in-flight syscall pins the file description, so
        # the FIN is never sent and both peers hang).
        _shutdown_close(self._sock)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            _shutdown_close(c)

    # ---- loops ---------------------------------------------------------
    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True,
                name=f"ray_tpu::rpc::{self._name}::conn").start()

    def _reader_loop(self, conn: socket.socket):
        write_lock = threading.Lock()
        try:
            peer = conn.getpeername()
        except OSError:
            peer = ("?", 0)
        try:
            try:
                wire.expect_preamble(conn)
            except wire.WireVersionMismatch:
                return   # wrong-version (or non-ray_tpu) peer: drop it
            except (wire.ConnectionClosed, OSError, EOFError):
                return
            while not self._stopped.is_set():
                try:
                    msg = wire.recv_msg(conn)
                except (wire.ConnectionClosed, OSError, EOFError):
                    return
                msg_id, method, payload = msg[0], msg[1], msg[2]
                token = msg[3] if len(msg) > 3 else None
                if not verbs_mod.is_control(method):
                    # Wire chaos point, receive side.  delay runs here
                    # on the reader thread deliberately: a slow link
                    # delays everything behind the frame, exactly like
                    # real queueing.  error replies like a torn wire;
                    # drop never dispatches (and so never replies).
                    try:
                        action = _hook(
                            "rpc.recv", verb=method,
                            peer=f"{peer[0]}:{peer[1]}",
                            peer_host=peer[0], peer_port=peer[1])
                    except Exception as e:
                        self._reply(conn, write_lock, msg_id, False,
                                    f"injected wire fault: {e}")
                        continue
                    if action == "drop":
                        continue
                    if action == "duplicate":
                        self._submit_dispatch(conn, write_lock, msg_id,
                                              method, payload, token)
                self._submit_dispatch(conn, write_lock, msg_id, method,
                                      payload, token)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _submit_dispatch(self, conn, write_lock, msg_id, method,
                         payload, token=None):
        with self._active_lock:
            pooled = self._active < self._pool_size
            if pooled:
                self._active += 1
        if pooled:
            def run():
                try:
                    self._dispatch(conn, write_lock, msg_id, method,
                                   payload, token)
                finally:
                    with self._active_lock:
                        self._active -= 1

            try:
                self._pool.submit(run)
                return
            except RuntimeError:      # pool stopped mid-stop
                with self._active_lock:
                    self._active -= 1
        threading.Thread(
            target=self._dispatch,
            args=(conn, write_lock, msg_id, method, payload, token),
            daemon=True,
            name=f"ray_tpu::rpc::{self._name}::call").start()

    def _dispatch(self, conn, write_lock, msg_id, method, payload,
                  token=None):
        entry = self._handlers.get(method)
        if entry is None:
            self._reply(conn, write_lock, msg_id, False,
                        f"no such method: {method}")
            return
        handler, is_async = entry
        if token is not None:
            # At-most-once: duplicates (client retries, duplicated
            # deliveries) get the first run's recorded reply.
            def replier(ok, result, _c=conn, _wl=write_lock, _m=msg_id):
                self._reply(_c, _wl, _m, ok, result)

            if not self.dedup_window.admit(token, replier):
                return
        if is_async:
            replied = threading.Event()

            def reply_cb(result):
                if not replied.is_set():
                    replied.set()
                    if token is not None:
                        self.dedup_window.complete(token, True, result)
                    self._reply(conn, write_lock, msg_id, True, result)

            try:
                handler(payload, reply_cb)
            except Exception:
                if not replied.is_set():
                    replied.set()
                    tb = traceback.format_exc()
                    if token is not None:
                        self.dedup_window.complete(token, False, tb)
                    self._reply(conn, write_lock, msg_id, False, tb)
            return
        try:
            result = handler(payload)
        except Exception:
            tb = traceback.format_exc()
            if token is not None:
                self.dedup_window.complete(token, False, tb)
            self._reply(conn, write_lock, msg_id, False, tb)
            return
        if token is not None:
            self.dedup_window.complete(token, True, result)
        self._reply(conn, write_lock, msg_id, True, result)

    def _reply(self, conn, write_lock, msg_id, ok, payload):
        try:
            wire.send_msg(conn, (msg_id, ok, payload), lock=write_lock)
        except (OSError, wire.ConnectionClosed):
            pass  # peer gone; nothing to tell it
