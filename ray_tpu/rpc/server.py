"""Threaded RPC server: the process-boundary front of a service surface.

Reference analogue: ``src/ray/rpc/grpc_server.h`` — a ``GrpcServer``
binds a port and dispatches each inbound call to a registered handler on
an io-context thread.  Here: one acceptor thread, one reader thread per
connection, and each request runs on its own dispatch thread so a
blocking handler (e.g. a worker lease waiting for dependencies) never
stalls pipelined requests on the same connection.

Handlers are ``name -> callable(payload) -> reply``.  A handler may
instead accept ``(payload, reply_cb)`` by registering with
``register_async`` — the reply is sent whenever ``reply_cb(result)``
fires, which maps 1:1 onto the runtime's callback-style surfaces
(``Raylet.request_worker_lease(spec, reply)``).
"""

from __future__ import annotations

import socket
import threading
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.rpc import wire


def _shutdown_close(sock: socket.socket):
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class RpcServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "rpc"):
        self._handlers: Dict[str, Tuple[Callable, bool]] = {}
        self._name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._stopped = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # Bounded dispatch pool (reference grpc_server.h: a fixed io
        # thread pool, not a thread per call).  Overflow takes a
        # dedicated thread instead of queueing: many handlers block on
        # other requests to this same server (lease dep-waits, gets),
        # so queueing behind them could deadlock.  Daemon threads: a
        # handler stuck in a long wait must not hang interpreter exit.
        from ray_tpu._private.config import get_config
        from ray_tpu._private.daemon_pool import DaemonPool
        self._pool_size = get_config().rpc_dispatch_pool_size
        self._pool = DaemonPool(self._pool_size,
                                name=f"ray_tpu::rpc::{name}::pool")
        self._active = 0
        self._active_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"ray_tpu::rpc::{name}::accept")
        self._accept_thread.start()

    # ---- registry ------------------------------------------------------
    def register(self, method: str, handler: Callable[[Any], Any]):
        """Sync handler: return value becomes the reply."""
        self._handlers[method] = (handler, False)

    def register_async(self, method: str,
                       handler: Callable[[Any, Callable], None]):
        """Callback handler: handler(payload, reply_cb); the reply is sent
        when reply_cb(result) is invoked (once)."""
        self._handlers[method] = (handler, True)

    def register_instance(self, obj, methods):
        """Expose the listed bound methods of ``obj`` as sync handlers."""
        for m in methods:
            self.register(m, getattr(obj, m))

    # ---- lifecycle -----------------------------------------------------
    def stop(self):
        self._stopped.set()
        self._pool.stop()
        # shutdown() before close(): a close alone does not tear the
        # connection down while another thread is blocked in recv on the
        # same fd (the in-flight syscall pins the file description, so
        # the FIN is never sent and both peers hang).
        _shutdown_close(self._sock)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            _shutdown_close(c)

    # ---- loops ---------------------------------------------------------
    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True,
                name=f"ray_tpu::rpc::{self._name}::conn").start()

    def _reader_loop(self, conn: socket.socket):
        write_lock = threading.Lock()
        try:
            try:
                wire.expect_preamble(conn)
            except wire.WireVersionMismatch:
                return   # wrong-version (or non-ray_tpu) peer: drop it
            except (wire.ConnectionClosed, OSError, EOFError):
                return
            while not self._stopped.is_set():
                try:
                    msg_id, method, payload = wire.recv_msg(conn)
                except (wire.ConnectionClosed, OSError, EOFError):
                    return
                self._submit_dispatch(conn, write_lock, msg_id, method,
                                      payload)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _submit_dispatch(self, conn, write_lock, msg_id, method,
                         payload):
        with self._active_lock:
            pooled = self._active < self._pool_size
            if pooled:
                self._active += 1
        if pooled:
            def run():
                try:
                    self._dispatch(conn, write_lock, msg_id, method,
                                   payload)
                finally:
                    with self._active_lock:
                        self._active -= 1

            try:
                self._pool.submit(run)
                return
            except RuntimeError:      # pool stopped mid-stop
                with self._active_lock:
                    self._active -= 1
        threading.Thread(
            target=self._dispatch,
            args=(conn, write_lock, msg_id, method, payload),
            daemon=True,
            name=f"ray_tpu::rpc::{self._name}::call").start()

    def _dispatch(self, conn, write_lock, msg_id, method, payload):
        entry = self._handlers.get(method)
        if entry is None:
            self._reply(conn, write_lock, msg_id, False,
                        f"no such method: {method}")
            return
        handler, is_async = entry
        if is_async:
            replied = threading.Event()

            def reply_cb(result):
                if not replied.is_set():
                    replied.set()
                    self._reply(conn, write_lock, msg_id, True, result)

            try:
                handler(payload, reply_cb)
            except Exception:
                if not replied.is_set():
                    replied.set()
                    self._reply(conn, write_lock, msg_id, False,
                                traceback.format_exc())
            return
        try:
            result = handler(payload)
        except Exception:
            self._reply(conn, write_lock, msg_id, False,
                        traceback.format_exc())
            return
        self._reply(conn, write_lock, msg_id, True, result)

    def _reply(self, conn, write_lock, msg_id, ok, payload):
        try:
            wire.send_msg(conn, (msg_id, ok, payload), lock=write_lock)
        except (OSError, wire.ConnectionClosed):
            pass  # peer gone; nothing to tell it
