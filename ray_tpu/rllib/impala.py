"""IMPALATrainer: async actor sampling + V-trace off-policy correction.

Parity: reference ``rllib/agents/impala/impala.py`` (decoupled
actor-learner: samplers run ahead of the learner, batches stream in as
they finish, importance-weighted V-trace targets correct the policy
lag — Espeholt et al. 2018, as ``vtrace.py`` in the reference) —
jax-first: V-trace is a ``lax.scan`` inside one jit program, and the
async pipeline is ``ray_tpu.wait`` over in-flight sample futures (the
runtime-streaming path PPO's synchronous collect never exercises).
"""

from __future__ import annotations

import pickle
from typing import Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy import ActorCritic, _jx

DEFAULT_CONFIG: Dict = {
    "num_workers": 2,
    "rollout_fragment_length": 128,   # T per trajectory fragment
    "train_batches_per_iter": 8,      # fragments consumed per train()
    "max_inflight_per_worker": 2,     # sampling runs ahead of learning
    "lr": 5e-4,
    "gamma": 0.99,
    "vf_coeff": 0.5,
    "ent_coeff": 0.01,
    "rho_bar": 1.0,                   # V-trace clipping
    "c_bar": 1.0,
    "hidden": (64, 64),
    "seed": 0,
}


def compute_vtrace(target_logp, behavior_logp, rewards, dones, values,
                   bootstrap_value, gamma: float, rho_bar: float,
                   c_bar: float):
    """Pure V-trace (Espeholt et al. 2018; reference vtrace.py):
    returns (vs targets [T], pg advantages [T]).  Backward lax.scan:
        delta_t = rho_t (r_t + gamma_t V_{t+1} - V_t)
        vs_t - V_t = delta_t + gamma_t c_t (vs_{t+1} - V_{t+1})
    Inputs are treated as constants (callers stop gradients)."""
    jax, jnp = _jx()
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    discount = gamma * (1.0 - dones)
    v_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]])
    deltas = rho * (rewards + discount * v_tp1 - values)

    def backward(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, dvs = jax.lax.scan(backward, jnp.zeros(()),
                          (deltas, discount, c), reverse=True)
    vs = values + dvs
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]])
    pg_adv = rho * (rewards + discount * vs_tp1 - values)
    return vs, pg_adv


def make_vtrace_update(policy: ActorCritic, gamma: float,
                       vf_coeff: float, ent_coeff: float,
                       rho_bar: float, c_bar: float):
    """One jit program: V-trace targets + policy gradient + value +
    entropy losses over one trajectory fragment."""
    import optax
    jax, jnp = _jx()
    opt = policy._opt

    @jax.jit
    def update(params, opt_state, batch):
        def loss_fn(p):
            from ray_tpu.rllib.policy import mlp_apply
            obs = batch["obs"]                     # [T, obs]
            logits = mlp_apply(p["pi"], obs)
            logp_all = jax.nn.log_softmax(logits)
            T = obs.shape[0]
            logp = logp_all[jnp.arange(T), batch["actions"]]
            values = mlp_apply(p["vf"], obs)[:, 0]  # [T]
            vs, pg_adv = compute_vtrace(
                jax.lax.stop_gradient(logp), batch["behavior_logp"],
                batch["rewards"], batch["dones"],
                jax.lax.stop_gradient(values),
                jnp.asarray(batch["bootstrap_value"]),
                gamma, rho_bar, c_bar)
            pg_loss = -jnp.mean(logp * jax.lax.stop_gradient(pg_adv))
            vf_loss = 0.5 * jnp.mean(
                (values - jax.lax.stop_gradient(vs)) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return loss, (vf_loss, entropy)

        (loss, (vf_loss, entropy)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, vf_loss, entropy

    return update


@ray_tpu.remote
class TrajectoryWorker:
    """Sampler emitting RAW trajectory fragments with behavior log-probs
    and a bootstrap value — what V-trace needs (the reference's
    RolloutWorker in IMPALA's execution plan)."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 seed: int = 0):
        from ray_tpu.rllib.rollout_worker import EnvLoop
        self.loop = EnvLoop(env_fn())
        self.policy = ActorCritic(seed=seed, **policy_config)

    def set_weights(self, weights: Dict):
        self.policy.set_weights(weights)
        return True

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        obs_dim = len(self.loop.obs)
        cols = {
            "obs": np.zeros((num_steps, obs_dim), np.float32),
            "actions": np.zeros(num_steps, np.int32),
            "rewards": np.zeros(num_steps, np.float32),
            "dones": np.zeros(num_steps, np.float32),
            "behavior_logp": np.zeros(num_steps, np.float32),
        }

        def policy_step(obs):
            action, logp, _v = self.policy.compute_actions(obs[None, :])
            return int(action[0]), float(logp[0])

        def record(t, obs, action, reward, _nxt, done, logp):
            cols["obs"][t] = obs
            cols["actions"][t] = action
            cols["behavior_logp"][t] = logp
            cols["rewards"][t] = reward
            cols["dones"][t] = float(done)

        self.loop.run(num_steps, policy_step, record)
        _, _, last_v = self.policy.compute_actions(
            self.loop.obs[None, :])
        cols["bootstrap_value"] = np.float32(last_v[0])
        cols["episode_rewards"] = self.loop.drain_episode_rewards()
        return cols


class IMPALATrainer:
    """Decoupled actor-learner loop: keep N sample futures in flight per
    worker, consume whichever finishes first (ray_tpu.wait), train on
    each fragment with V-trace, refresh that worker's weights, resubmit
    — samplers never block on the learner and vice versa."""

    def __init__(self, env_fn: Callable, config: Optional[Dict] = None):
        self.config = dict(DEFAULT_CONFIG)
        self.config.update(config or {})
        cfg = self.config
        probe = env_fn()
        policy_config = {
            "obs_size": probe.observation_size,
            "num_actions": probe.num_actions,
            "hidden": tuple(cfg["hidden"]),
            "lr": cfg["lr"],
        }
        self.policy = ActorCritic(seed=cfg["seed"], **policy_config)
        self._update = make_vtrace_update(
            self.policy, cfg["gamma"], cfg["vf_coeff"],
            cfg["ent_coeff"], cfg["rho_bar"], cfg["c_bar"])
        self.workers = [
            TrajectoryWorker.remote(env_fn, policy_config,
                                    seed=3000 + i)
            for i in range(cfg["num_workers"])]
        ray_tpu.get([w.set_weights.remote(self.policy.get_weights())
                     for w in self.workers])
        # Prime the pipeline: futures owned per worker.
        self._inflight: Dict = {}
        for w in self.workers:
            for _ in range(cfg["max_inflight_per_worker"]):
                ref = w.sample.remote(cfg["rollout_fragment_length"])
                self._inflight[ref] = w
        self.iteration = 0
        self.timesteps_total = 0

    def train(self) -> Dict:
        cfg = self.config
        stats = {}
        episode_rewards = []
        consumed = 0
        while consumed < cfg["train_batches_per_iter"]:
            ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break
            ref = ready[0]
            worker = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            episode_rewards.extend(batch.pop("episode_rewards"))
            self.policy.params, self.policy.opt_state, loss, vf, ent = \
                self._update(self.policy.params, self.policy.opt_state,
                             batch)
            stats = {"loss": float(loss), "vf_loss": float(vf),
                     "entropy": float(ent)}
            self.timesteps_total += len(batch["obs"])
            consumed += 1
            # Refresh the worker's policy, then keep it sampling.
            worker.set_weights.remote(self.policy.get_weights())
            new_ref = worker.sample.remote(
                cfg["rollout_fragment_length"])
            self._inflight[new_ref] = worker
        self.iteration += 1
        rewards = np.asarray(episode_rewards, np.float32)
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "batches_this_iter": consumed,
            "episodes_this_iter": len(rewards),
            "episode_reward_mean": float(rewards.mean())
            if len(rewards) else float("nan"),
            **stats,
        }

    def compute_action(self, obs: np.ndarray) -> int:
        action, _l, _v = self.policy.compute_actions(
            np.asarray(obs, np.float32)[None, :])
        return int(action[0])

    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump({"weights": self.policy.get_weights(),
                         "iteration": self.iteration,
                         "config": self.config}, f)
        return path

    def restore(self, path: str):
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.policy.set_weights(state["weights"])
        self.iteration = state["iteration"]

    def stop(self):
        self._inflight.clear()
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
