"""RolloutWorker + WorkerSet: the sampling fleet.

Parity: reference ``rllib/evaluation/rollout_worker.py`` (an actor
holding env + policy, producing sample batches) and
``rllib/evaluation/worker_set.py`` (the fleet with weight broadcast and
parallel sampling).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy import ActorCritic, compute_gae


@ray_tpu.remote
class RolloutWorker:
    """One sampler: steps its env with the current policy and returns
    GAE-processed batches."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 gamma: float = 0.99, lam: float = 0.95, seed: int = 0):
        self.env = env_fn()
        self.policy = ActorCritic(seed=seed, **policy_config)
        self.gamma = gamma
        self.lam = lam
        self._obs = self.env.reset()
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, weights: Dict):
        self.policy.set_weights(weights)
        return True

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        obs_buf = np.zeros((num_steps, len(self._obs)), dtype=np.float32)
        act_buf = np.zeros(num_steps, dtype=np.int32)
        rew_buf = np.zeros(num_steps, dtype=np.float32)
        done_buf = np.zeros(num_steps, dtype=np.float32)
        logp_buf = np.zeros(num_steps, dtype=np.float32)
        val_buf = np.zeros(num_steps, dtype=np.float32)
        for t in range(num_steps):
            action, logp, value = self.policy.compute_actions(
                self._obs[None, :])
            obs_buf[t] = self._obs
            act_buf[t] = action[0]
            logp_buf[t] = logp[0]
            val_buf[t] = value[0]
            self._obs, reward, done, _info = self.env.step(int(action[0]))
            rew_buf[t] = reward
            done_buf[t] = float(done)
            self._episode_reward += reward
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs = self.env.reset()
        _, _, last_value = self.policy.compute_actions(self._obs[None, :])
        advantages, returns = compute_gae(
            rew_buf, val_buf, done_buf, float(last_value[0]),
            self.gamma, self.lam)
        episode_rewards, self._episode_rewards = self._episode_rewards, []
        return {
            "obs": obs_buf, "actions": act_buf, "logp_old": logp_buf,
            "advantages": advantages, "returns": returns,
            "episode_rewards": np.asarray(episode_rewards,
                                          dtype=np.float32),
        }


class WorkerSet:
    """The rollout fleet (worker_set.py parity): parallel sampling and
    weight broadcast over plain actor calls."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 num_workers: int, gamma: float, lam: float):
        self.workers = [
            RolloutWorker.remote(env_fn, policy_config, gamma=gamma,
                                 lam=lam, seed=1000 + i)
            for i in range(num_workers)]

    def broadcast_weights(self, weights: Dict):
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers])

    def sample(self, steps_per_worker: int) -> List[Dict[str, np.ndarray]]:
        return ray_tpu.get(
            [w.sample.remote(steps_per_worker) for w in self.workers])

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
