"""RolloutWorker + WorkerSet: the sampling fleet.

Parity: reference ``rllib/evaluation/rollout_worker.py`` (an actor
holding env + policy, producing sample batches) and
``rllib/evaluation/worker_set.py`` (the fleet with weight broadcast and
parallel sampling).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy import ActorCritic, compute_gae


class EnvLoop:
    """Shared env-stepping scaffold for every sampler (PPO rollout, DQN
    transition, IMPALA trajectory workers): reset-on-done, episode
    reward bookkeeping persisting across sample calls, and the
    final-observation hand-off for bootstrapping.  Samplers differ only
    in what they record per step."""

    def __init__(self, env):
        self.env = env
        self.obs = env.reset()
        self._episode_reward = 0.0
        self._completed: List[float] = []

    def run(self, num_steps: int, policy_step, on_transition):
        """``policy_step(obs) -> (action:int, extras)``;
        ``on_transition(t, obs, action, reward, next_obs, done,
        extras)`` records the step."""
        for t in range(num_steps):
            action, extras = policy_step(self.obs)
            nxt, reward, done, _info = self.env.step(int(action))
            on_transition(t, self.obs, action, reward, nxt, done,
                          extras)
            self._episode_reward += reward
            if done:
                self._completed.append(self._episode_reward)
                self._episode_reward = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = nxt

    def drain_episode_rewards(self) -> np.ndarray:
        out, self._completed = self._completed, []
        return np.asarray(out, dtype=np.float32)


@ray_tpu.remote
class RolloutWorker:
    """One sampler: steps its env with the current policy and returns
    GAE-processed batches."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 gamma: float = 0.99, lam: float = 0.95, seed: int = 0):
        self.loop = EnvLoop(env_fn())
        self.policy = ActorCritic(seed=seed, **policy_config)
        self.gamma = gamma
        self.lam = lam

    def set_weights(self, weights: Dict):
        self.policy.set_weights(weights)
        return True

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        obs_dim = len(self.loop.obs)
        obs_buf = np.zeros((num_steps, obs_dim), dtype=np.float32)
        act_buf = np.zeros(num_steps, dtype=np.int32)
        rew_buf = np.zeros(num_steps, dtype=np.float32)
        done_buf = np.zeros(num_steps, dtype=np.float32)
        logp_buf = np.zeros(num_steps, dtype=np.float32)
        val_buf = np.zeros(num_steps, dtype=np.float32)

        def policy_step(obs):
            action, logp, value = self.policy.compute_actions(
                obs[None, :])
            return int(action[0]), (float(logp[0]), float(value[0]))

        def record(t, obs, action, reward, _nxt, done, extras):
            obs_buf[t] = obs
            act_buf[t] = action
            logp_buf[t], val_buf[t] = extras
            rew_buf[t] = reward
            done_buf[t] = float(done)

        self.loop.run(num_steps, policy_step, record)
        _, _, last_value = self.policy.compute_actions(
            self.loop.obs[None, :])
        advantages, returns = compute_gae(
            rew_buf, val_buf, done_buf, float(last_value[0]),
            self.gamma, self.lam)
        return {
            "obs": obs_buf, "actions": act_buf, "logp_old": logp_buf,
            "advantages": advantages, "returns": returns,
            "episode_rewards": self.loop.drain_episode_rewards(),
        }


class WorkerSet:
    """The rollout fleet (worker_set.py parity): parallel sampling and
    weight broadcast over plain actor calls."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 num_workers: int, gamma: float, lam: float):
        self.workers = [
            RolloutWorker.remote(env_fn, policy_config, gamma=gamma,
                                 lam=lam, seed=1000 + i)
            for i in range(num_workers)]

    def broadcast_weights(self, weights: Dict):
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers])

    def sample(self, steps_per_worker: int) -> List[Dict[str, np.ndarray]]:
        return ray_tpu.get(
            [w.sample.remote(steps_per_worker) for w in self.workers])

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
