"""PPOTrainer: clipped-surrogate PPO over the rollout fleet.

Parity: reference ``rllib/agents/ppo/ppo.py`` (Trainer: config, the
collect -> shuffle -> minibatch-SGD -> broadcast loop, ``train()``
returning a metrics dict, ``save``/``restore``), re-designed TPU-first:
the learner's whole SGD epoch is jit-compiled jax (policy.py); sampling
scales as framework actors (rollout_worker.py); weights travel as numpy
pytrees through the object store.
"""

from __future__ import annotations

import pickle
from typing import Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.policy import ActorCritic
from ray_tpu.rllib.rollout_worker import WorkerSet

DEFAULT_CONFIG: Dict = {
    "num_workers": 2,
    "rollout_fragment_length": 256,   # steps per worker per iteration
    "num_sgd_epochs": 6,
    "sgd_minibatch_size": 128,
    "lr": 3e-4,
    "gamma": 0.99,
    "lambda": 0.95,
    "clip_eps": 0.2,
    "vf_coeff": 0.5,
    "ent_coeff": 0.01,
    "hidden": (64, 64),
    "seed": 0,
}


class PPOTrainer:
    def __init__(self, env_fn: Callable, config: Optional[Dict] = None):
        self.config = dict(DEFAULT_CONFIG)
        self.config.update(config or {})
        cfg = self.config
        probe_env = env_fn()
        policy_config = {
            "obs_size": probe_env.observation_size,
            "num_actions": probe_env.num_actions,
            "hidden": tuple(cfg["hidden"]),
            "lr": cfg["lr"],
        }
        self.policy = ActorCritic(seed=cfg["seed"], **policy_config)
        self.workers = WorkerSet(env_fn, policy_config,
                                 num_workers=cfg["num_workers"],
                                 gamma=cfg["gamma"], lam=cfg["lambda"])
        self.iteration = 0
        self._rng = np.random.default_rng(cfg["seed"])

    # ---- one training iteration (ppo.py execution plan parity) ---------
    def train(self) -> Dict:
        cfg = self.config
        self.workers.broadcast_weights(self.policy.get_weights())
        batches = self.workers.sample(cfg["rollout_fragment_length"])
        episode_rewards = np.concatenate(
            [b.pop("episode_rewards") for b in batches])
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in batches[0]}
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(batch["obs"])
        stats = {}
        for _epoch in range(cfg["num_sgd_epochs"]):
            order = self._rng.permutation(n)
            for start in range(0, n, cfg["sgd_minibatch_size"]):
                idx = order[start:start + cfg["sgd_minibatch_size"]]
                if len(idx) < 2:
                    continue
                minibatch = {k: v[idx] for k, v in batch.items()}
                stats = self.policy.sgd_step(
                    minibatch, cfg["clip_eps"], cfg["vf_coeff"],
                    cfg["ent_coeff"])
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_this_iter": n,
            "episodes_this_iter": len(episode_rewards),
            "episode_reward_mean": float(episode_rewards.mean())
            if len(episode_rewards) else float("nan"),
            "episode_reward_max": float(episode_rewards.max())
            if len(episode_rewards) else float("nan"),
            **stats,
        }

    # ---- checkpointing (Trainer.save/restore parity) --------------------
    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump({"weights": self.policy.get_weights(),
                         "iteration": self.iteration,
                         "config": self.config}, f)
        return path

    def restore(self, path: str):
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.policy.set_weights(state["weights"])
        self.iteration = state["iteration"]

    def compute_action(self, obs: np.ndarray) -> int:
        action, _logp, _value = self.policy.compute_actions(
            np.asarray(obs, dtype=np.float32)[None, :])
        return int(action[0])

    def stop(self):
        self.workers.stop()
