"""ray_tpu.rllib — reinforcement learning on the task/actor core.

Parity: a focused slice of the reference's ``rllib/`` (118k LoC):
``RolloutWorker``/``WorkerSet`` (evaluation/), the PPO trainer
(agents/ppo/) with GAE and clipped-surrogate loss, and Trainer
save/restore — jax-first (jit-compiled learner, numpy-pytree weight
shipping, actor-fleet sampling).  Algorithms beyond PPO follow the same
WorkerSet + jit-learner shape.
"""

from ray_tpu.rllib.dqn import DQNTrainer, QPolicy, TransitionWorker
from ray_tpu.rllib.env import CartPole
from ray_tpu.rllib.impala import IMPALATrainer, TrajectoryWorker
from ray_tpu.rllib.policy import ActorCritic, compute_gae
from ray_tpu.rllib.ppo import DEFAULT_CONFIG, PPOTrainer
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)
from ray_tpu.rllib.rollout_worker import RolloutWorker, WorkerSet

__all__ = ["CartPole", "ActorCritic", "compute_gae", "PPOTrainer",
           "DEFAULT_CONFIG", "RolloutWorker", "WorkerSet",
           "DQNTrainer", "QPolicy", "TransitionWorker",
           "IMPALATrainer", "TrajectoryWorker",
           "ReplayBuffer", "PrioritizedReplayBuffer"]
