"""Replay buffers for off-policy learners.

Parity: reference ``rllib/execution/replay_buffer.py`` —
``ReplayBuffer`` (uniform ring buffer) and
``PrioritizedReplayBuffer`` (proportional prioritization with
importance-sampling weights, Schaul et al. 2015) — numpy-columnar so a
sampled minibatch ships to the jit learner as one contiguous batch per
field (TPU-friendly: no per-transition Python objects).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over columnar transition storage."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        """Append a batch of transitions (same-length arrays per key)."""
        n = len(next(iter(batch.values())))
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity, *v.shape[1:]), dtype=v.dtype)
                for k, v in batch.items()}
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._cols.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization: P(i) ~ p_i^alpha, IS weights
    w_i = (N * P(i))^-beta normalized by max (Schaul et al.;
    reference replay_buffer.py PrioritizedReplayBuffer)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = alpha
        self.beta = beta
        self._prios = np.zeros(capacity, dtype=np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        idx = super().add_batch(batch)
        self._prios[idx] = self._max_prio ** self.alpha
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        prios = self._prios[:self._size]
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        # Normalize by the buffer-GLOBAL max weight (the min-priority
        # item's), so the bias correction is consistent across batches
        # (Schaul et al. 3.4; reference replay_buffer.py).
        max_weight = (self._size * probs.min()) ** (-self.beta)
        weights /= max_weight
        out = {k: v[idx] for k, v in self._cols.items()}
        out["weights"] = weights.astype(np.float32)
        out["indices"] = idx
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray):
        prios = np.abs(td_errors) + 1e-6
        self._prios[indices] = prios ** self.alpha
        self._max_prio = max(self._max_prio, float(prios.max()))
