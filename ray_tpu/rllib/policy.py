"""JAX actor-critic policy + PPO loss — the TPU compute path.

Parity: reference ``rllib/policy/`` + ``rllib/agents/ppo/ppo_*_policy.py``
(clipped-surrogate PPO loss, GAE advantages), re-designed jax-first: the
policy is pure functions (init/apply/loss) jit-compiled once, parameters
are pytrees shipped between the trainer and rollout workers as numpy,
and the SGD step runs under ``jax.jit`` so XLA fuses the whole update
onto the accelerator (MXU matmuls, no per-sample Python).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np


def _jx():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def init_mlp_params(rng_seed: int, sizes) -> Dict:
    """He-initialized MLP pytree: sizes = [in, hidden..., out]."""
    jax, jnp = _jx()
    key = jax.random.PRNGKey(rng_seed)
    params = {}
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = jax.random.normal(
            sub, (fan_in, fan_out)) * np.sqrt(2.0 / fan_in)
        params[f"b{i}"] = jnp.zeros((fan_out,))
    return params


def mlp_apply(params: Dict, x):
    jax, jnp = _jx()
    n_layers = len(params) // 2
    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = jnp.tanh(x)
    return x


class ActorCritic:
    """Shared-nothing actor + critic MLPs with jit-compiled action
    sampling and PPO update."""

    def __init__(self, obs_size: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64), lr: float = 3e-4,
                 seed: int = 0):
        import optax
        jax, jnp = _jx()
        self.num_actions = num_actions
        self.params = {
            "pi": init_mlp_params(seed, [obs_size, *hidden, num_actions]),
            "vf": init_mlp_params(seed + 1, [obs_size, *hidden, 1]),
        }
        self._opt = optax.adam(lr)
        self.opt_state = self._opt.init(self.params)

        @jax.jit
        def act(params, obs, key):
            logits = mlp_apply(params["pi"], obs)
            action = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(obs.shape[0]), action]
            value = mlp_apply(params["vf"], obs)[:, 0]
            return action, logp, value

        @jax.jit
        def update(params, opt_state, batch, clip_eps, vf_coeff,
                   ent_coeff):
            def loss_fn(p):
                logits = mlp_apply(p["pi"], batch["obs"])
                logp_all = jax.nn.log_softmax(logits)
                logp = logp_all[jnp.arange(batch["obs"].shape[0]),
                                batch["actions"]]
                ratio = jnp.exp(logp - batch["logp_old"])
                adv = batch["advantages"]
                surrogate = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv)
                value = mlp_apply(p["vf"], batch["obs"])[:, 0]
                vf_loss = jnp.mean((value - batch["returns"]) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
                loss = (-jnp.mean(surrogate) + vf_coeff * vf_loss -
                        ent_coeff * entropy)
                return loss, (vf_loss, entropy)

            (loss, (vf_loss, entropy)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, vf_loss, entropy

        self._act = act
        self._update = update
        self._key = jax.random.PRNGKey(seed + 2)

    # ---- rollout-side ---------------------------------------------------
    def compute_actions(self, obs: np.ndarray):
        jax, _ = _jx()
        self._key, sub = jax.random.split(self._key)
        action, logp, value = self._act(self.params, obs, sub)
        return (np.asarray(action), np.asarray(logp), np.asarray(value))

    # ---- trainer-side ---------------------------------------------------
    def sgd_step(self, batch: Dict[str, np.ndarray], clip_eps: float,
                 vf_coeff: float, ent_coeff: float) -> Dict[str, float]:
        self.params, self.opt_state, loss, vf_loss, entropy = \
            self._update(self.params, self.opt_state, batch,
                         clip_eps, vf_coeff, ent_coeff)
        return {"loss": float(loss), "vf_loss": float(vf_loss),
                "entropy": float(entropy)}

    # ---- weights shipping ----------------------------------------------
    def get_weights(self) -> Dict:
        import jax
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights: Dict):
        self.params = weights


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                dones: np.ndarray, last_value: float,
                gamma: float, lam: float):
    """Generalized advantage estimation (reference: ppo utils)."""
    n = len(rewards)
    advantages = np.zeros(n, dtype=np.float32)
    gae = 0.0
    next_value = last_value
    for t in reversed(range(n)):
        nonterminal = 1.0 - float(dones[t])
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        advantages[t] = gae
        next_value = values[t]
    returns = advantages + values
    return advantages, returns


@functools.lru_cache(maxsize=None)
def _noop():
    return None
