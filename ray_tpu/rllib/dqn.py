"""DQNTrainer: double DQN with (prioritized) replay over the fleet.

Parity: reference ``rllib/agents/dqn/dqn.py`` (Trainer: epsilon-greedy
exploration schedule, replay buffer, target network sync, the
store->replay->train execution plan) — jax-first: the TD update is one
jit program (double-DQN targets, Huber loss, IS weights), transitions
are columnar numpy, and sampling scales as framework actors.
"""

from __future__ import annotations

import pickle
from typing import Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy import _jx, init_mlp_params, mlp_apply
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)

DEFAULT_CONFIG: Dict = {
    "num_workers": 2,
    "rollout_fragment_length": 64,     # steps per worker per round
    "buffer_size": 50_000,
    "prioritized_replay": True,
    "learning_starts": 500,            # min transitions before SGD
    "train_batch_size": 64,
    "sgd_rounds_per_iter": 32,         # minibatches per train()
    "target_network_update_freq": 300,  # SGD steps between target syncs
    "gamma": 0.99,
    "lr": 1e-3,
    "hidden": (64, 64),
    "epsilon_initial": 1.0,
    "epsilon_final": 0.05,
    "epsilon_timesteps": 4_000,        # linear decay horizon
    "double_q": True,
    "seed": 0,
}


class QPolicy:
    """Q-network with jit-compiled epsilon-greedy action selection and
    double-DQN TD update (dqn_tf_policy.py / dqn_torch_policy.py
    parity, as pure jax functions)."""

    def __init__(self, obs_size: int, num_actions: int,
                 hidden=(64, 64), lr: float = 1e-3, gamma: float = 0.99,
                 double_q: bool = True, seed: int = 0):
        import optax
        jax, jnp = _jx()
        self.num_actions = num_actions
        self.params = init_mlp_params(seed, [obs_size, *hidden,
                                             num_actions])
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self._opt = optax.adam(lr)
        self.opt_state = self._opt.init(self.params)

        @jax.jit
        def act(params, obs, epsilon, key):
            q = mlp_apply(params, obs)                 # [B, A]
            greedy = jnp.argmax(q, axis=-1)
            k1, k2 = jax.random.split(key)
            random_a = jax.random.randint(
                k1, greedy.shape, 0, num_actions)
            explore = jax.random.uniform(k2, greedy.shape) < epsilon
            return jnp.where(explore, random_a, greedy)

        @jax.jit
        def update(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = mlp_apply(p, batch["obs"])
                q_sa = q[jnp.arange(q.shape[0]), batch["actions"]]
                q_next_t = mlp_apply(target_params, batch["next_obs"])
                if double_q:
                    # Online net selects, target net evaluates.
                    a_star = jnp.argmax(
                        mlp_apply(p, batch["next_obs"]), axis=-1)
                    q_next = q_next_t[jnp.arange(q.shape[0]), a_star]
                else:
                    q_next = jnp.max(q_next_t, axis=-1)
                target = batch["rewards"] + gamma * \
                    (1.0 - batch["dones"]) * q_next
                td = q_sa - jax.lax.stop_gradient(target)
                huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                                  jnp.abs(td) - 0.5)
                w = batch.get("weights", jnp.ones_like(huber))
                return jnp.mean(w * huber), td

            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._act = act
        self._update = update
        self._key = jax.random.PRNGKey(seed + 2)

    def compute_actions(self, obs: np.ndarray,
                        epsilon: float = 0.0) -> np.ndarray:
        jax, _ = _jx()
        self._key, sub = jax.random.split(self._key)
        return np.asarray(self._act(self.params, obs,
                                    np.float32(epsilon), sub))

    def sgd_step(self, batch: Dict[str, np.ndarray]):
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "indices"}
        self.params, self.opt_state, loss, td = self._update(
            self.params, self.target_params, self.opt_state, jb)
        return float(loss), np.asarray(td)

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)

    def get_weights(self) -> Dict:
        import jax
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights: Dict):
        self.params = weights


@ray_tpu.remote
class TransitionWorker:
    """Sampler for off-policy learners: steps its env epsilon-greedily
    and returns raw transition batches (obs, action, reward, next_obs,
    done) — the store->replay half of the DQN execution plan."""

    def __init__(self, env_fn: Callable, policy_config: Dict,
                 seed: int = 0):
        from ray_tpu.rllib.rollout_worker import EnvLoop
        self.loop = EnvLoop(env_fn())
        self.policy = QPolicy(seed=seed, **policy_config)

    def set_weights(self, weights: Dict):
        self.policy.set_weights(weights)
        return True

    def sample(self, num_steps: int, epsilon: float
               ) -> Dict[str, np.ndarray]:
        obs_dim = len(self.loop.obs)
        cols = {
            "obs": np.zeros((num_steps, obs_dim), np.float32),
            "actions": np.zeros(num_steps, np.int32),
            "rewards": np.zeros(num_steps, np.float32),
            "next_obs": np.zeros((num_steps, obs_dim), np.float32),
            "dones": np.zeros(num_steps, np.float32),
        }

        def policy_step(obs):
            return int(self.policy.compute_actions(
                obs[None, :], epsilon)[0]), None

        def record(t, obs, action, reward, nxt, done, _extras):
            cols["obs"][t] = obs
            cols["actions"][t] = action
            cols["rewards"][t] = reward
            cols["next_obs"][t] = nxt
            cols["dones"][t] = float(done)

        self.loop.run(num_steps, policy_step, record)
        cols["episode_rewards"] = self.loop.drain_episode_rewards()
        return cols


class DQNTrainer:
    """The collect -> replay -> train loop (dqn.py execution plan)."""

    def __init__(self, env_fn: Callable, config: Optional[Dict] = None):
        self.config = dict(DEFAULT_CONFIG)
        self.config.update(config or {})
        cfg = self.config
        probe = env_fn()
        self._policy_config = {
            "obs_size": probe.observation_size,
            "num_actions": probe.num_actions,
            "hidden": tuple(cfg["hidden"]),
            "lr": cfg["lr"],
            "gamma": cfg["gamma"],
            "double_q": cfg["double_q"],
        }
        self.policy = QPolicy(seed=cfg["seed"], **self._policy_config)
        self.workers = [
            TransitionWorker.remote(env_fn, self._policy_config,
                                    seed=2000 + i)
            for i in range(cfg["num_workers"])]
        if cfg["prioritized_replay"]:
            self.buffer = PrioritizedReplayBuffer(
                cfg["buffer_size"], seed=cfg["seed"])
        else:
            self.buffer = ReplayBuffer(cfg["buffer_size"],
                                       seed=cfg["seed"])
        self.iteration = 0
        self.timesteps_total = 0
        self._sgd_steps = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.timesteps_total / cfg["epsilon_timesteps"])
        return cfg["epsilon_initial"] + frac * (
            cfg["epsilon_final"] - cfg["epsilon_initial"])

    def train(self) -> Dict:
        cfg = self.config
        eps = self._epsilon()
        weights = self.policy.get_weights()
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self.workers])
        batches = ray_tpu.get([
            w.sample.remote(cfg["rollout_fragment_length"], eps)
            for w in self.workers])
        episode_rewards = np.concatenate(
            [b.pop("episode_rewards") for b in batches])
        for b in batches:
            n = len(b["obs"])
            self.buffer.add_batch(b)
            self.timesteps_total += n

        loss = float("nan")
        if len(self.buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["sgd_rounds_per_iter"]):
                batch = self.buffer.sample(cfg["train_batch_size"])
                loss, td = self.policy.sgd_step(batch)
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(batch["indices"], td)
                self._sgd_steps += 1
                if self._sgd_steps % \
                        cfg["target_network_update_freq"] == 0:
                    self.policy.sync_target()
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps_total,
            "buffer_size": len(self.buffer),
            "epsilon": eps,
            "loss": loss,
            "episodes_this_iter": len(episode_rewards),
            "episode_reward_mean": float(episode_rewards.mean())
            if len(episode_rewards) else float("nan"),
        }

    def compute_action(self, obs: np.ndarray) -> int:
        return int(self.policy.compute_actions(
            np.asarray(obs, np.float32)[None, :], epsilon=0.0)[0])

    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump({"weights": self.policy.get_weights(),
                         "iteration": self.iteration,
                         "timesteps_total": self.timesteps_total,
                         "config": self.config}, f)
        return path

    def restore(self, path: str):
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.policy.set_weights(state["weights"])
        self.policy.sync_target()
        self.iteration = state["iteration"]
        self.timesteps_total = state["timesteps_total"]

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
