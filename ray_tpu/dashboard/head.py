"""Dashboard-lite: the head's REST + metrics endpoint.

Parity: reference ``dashboard/head.py`` + modules (node/actor/job views
aggregated from the GCS, ``/metrics`` Prometheus scrape via the metrics
agent, ``datacenter.py`` cluster rollups).  The React client is out of
scope; this serves the same data as JSON for tools and humans:

    GET /api/cluster            totals, availability, node count, jobs
    GET /api/nodes              node table (state, resources)
    GET /api/actors             actor table (state, restarts, class)
    GET /api/tasks              task lifecycle records (task-event
                                pipeline; ?state= ?name= ?limit= filters)
    GET /api/tasks/summary      per-function rollup + loss accounting
    GET /api/latency            task-dispatch latency by stage (p50/p99)
    GET /api/profile            critical-path job profile (?job_id=,
                                ?top_k= — stage/node/edge attribution)
    GET /api/placement_groups   PG table (state, bundles)
    GET /api/jobs               job submissions (when a JobManager runs)
    GET /metrics                Prometheus text exposition
    GET /                       tiny HTML overview
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu._private.metrics_agent import get_metrics_registry


class Dashboard:
    def __init__(self, cluster, job_manager=None, host: str = "127.0.0.1",
                 port: int = 0):
        self._cluster = cluster
        self._job_manager = job_manager
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *_args):       # no stderr spam
                pass

            def do_GET(self):
                try:
                    dashboard._route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:           # noqa: BLE001
                    self.send_error(500, str(e))

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ray_tpu::dashboard")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.address[0]}:{self.address[1]}"

    # ---- routing --------------------------------------------------------
    def _route(self, req: BaseHTTPRequestHandler):
        from urllib.parse import parse_qsl
        path, _, query = req.path.partition("?")
        path = path.rstrip("/") or "/"
        params = dict(parse_qsl(query))
        if path == "/metrics":
            self._send(req, get_metrics_registry().render_prometheus(),
                       content_type="text/plain; version=0.0.4")
        elif path == "/api/cluster":
            self._send_json(req, self._cluster_view())
        elif path == "/api/nodes":
            self._send_json(req, self._nodes())
        elif path == "/api/node_stats":
            self._send_json(req, self._node_stats())
        elif path == "/api/actors":
            self._send_json(req, self._actors())
        elif path == "/api/tasks":
            self._send_json(req, self._tasks(params))
        elif path == "/api/tasks/summary":
            from ray_tpu.experimental.state.api import \
                summarize_tasks_from_cluster
            self._send_json(req,
                            summarize_tasks_from_cluster(self._cluster))
        elif path == "/api/latency":
            from ray_tpu.gcs.task_events import flushed_manager
            mgr = flushed_manager(self._cluster.gcs)
            self._send_json(req, mgr.latency_summary()
                            if mgr is not None else {})
        elif path == "/api/profile":
            from ray_tpu.experimental.state.api import \
                profile_job_from_cluster
            try:
                top_k = int(params.get("top_k", 3))
            except ValueError:
                top_k = 3
            self._send_json(req, profile_job_from_cluster(
                self._cluster, params.get("job_id") or params.get("job"),
                top_k=top_k))
        elif path == "/api/placement_groups":
            self._send_json(req, self._cluster.gcs
                            .placement_group_manager.table())
        elif path == "/api/jobs":
            self._send_json(req, self._jobs())
        elif path == "/":
            self._send(req, self._index_html(), content_type="text/html")
        else:
            req.send_error(404, "unknown route")

    # ---- views ----------------------------------------------------------
    def _cluster_view(self) -> dict:
        view = self._cluster.gcs.resource_manager.view
        nodes = self._nodes()
        return {
            "total_resources": view.total_cluster_resources(),
            "available_resources": view.available_cluster_resources(),
            "alive_nodes": sum(1 for n in nodes
                               if n.get("state") == "ALIVE"),
            "dead_nodes": sum(1 for n in nodes
                              if n.get("state") == "DEAD"),
            "jobs": self._jobs(),
        }

    def _nodes(self) -> list:
        out = []
        for node_id, info in \
                self._cluster.gcs.node_manager.get_all_node_info().items():
            row = {"node_id": node_id.hex(),
                   "name": info.get("node_name", ""),
                   "state": info.get("state"),
                   "resources": info.get("resources", {})}
            out.append(row)
        return out

    def _node_stats(self) -> list:
        """Per-node physical stats (reporter-module parity): each
        node's psutil sample rides its resource report; remote
        node-hosts' latest reports are cached on their proxies."""
        out = []
        from ray_tpu._private.debug import swallow
        for raylet in self._cluster.raylets():
            try:
                report = raylet.get_resource_report()
            except Exception as e:
                swallow.noted("dashboard.node_stats", e)
                continue
            out.append({
                "node_id": raylet.node_id.hex(),
                "name": getattr(raylet, "node_name", ""),
                "load": report.get("load", {}),
                "host_stats": report.get("host_stats"),
            })
        return out

    def _actors(self) -> list:
        return [info for _aid, info in
                self._cluster.gcs.actor_manager.all_actor_info().items()]

    def _tasks(self, params: dict) -> list:
        from ray_tpu.experimental.state.api import tasks_from_cluster
        filters = [(key, "=", params[key])
                   for key in ("state", "name", "job_id", "node_id")
                   if key in params]
        try:
            limit = int(params.get("limit", 100))
            offset = int(params.get("offset", 0))
        except ValueError:
            # Client typo (?limit=abc) is a client error, not a 500.
            limit, offset = 100, 0
        return tasks_from_cluster(self._cluster, filters or None,
                                  limit, offset)

    def _jobs(self) -> list:
        if self._job_manager is None:
            return []
        from dataclasses import asdict
        return [asdict(j) for j in self._job_manager.list_jobs()]

    def _index_html(self) -> str:
        view = self._cluster_view()
        rows = "".join(
            f"<tr><td>{n['name'] or n['node_id'][:12]}</td>"
            f"<td>{n['state']}</td>"
            f"<td>{json.dumps(n['resources'])}</td></tr>"
            for n in self._nodes())
        return (
            "<html><head><title>ray_tpu dashboard</title></head><body>"
            f"<h2>ray_tpu cluster — {view['alive_nodes']} node(s) alive"
            "</h2>"
            f"<p>total: {json.dumps(view['total_resources'])}</p>"
            f"<p>available: "
            f"{json.dumps(view['available_resources'])}</p>"
            "<table border=1><tr><th>node</th><th>state</th>"
            "<th>resources</th></tr>" + rows + "</table>"
            "<p>endpoints: /api/cluster /api/nodes /api/actors "
            "/api/tasks /api/tasks/summary /api/latency /api/profile "
            "/api/placement_groups /api/jobs /metrics</p>"
            "</body></html>")

    # ---- plumbing -------------------------------------------------------
    @staticmethod
    def _send(req, body: str, content_type: str = "application/json"):
        data = body.encode()
        req.send_response(200)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _send_json(self, req, obj):
        self._send(req, json.dumps(obj, default=str))

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(cluster, job_manager=None,
                    port: int = 0) -> Optional[Dashboard]:
    try:
        return Dashboard(cluster, job_manager=job_manager, port=port)
    except OSError:
        return None
