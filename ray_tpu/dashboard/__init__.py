from ray_tpu.dashboard.head import Dashboard

__all__ = ["Dashboard"]
