"""Per-node physical stats (reference ``dashboard/modules/reporter``:
each node's agent samples CPU/memory/disk via psutil and reports them
up; the head aggregates).

Here the stats ride the resource-report channel every node already
sends (``get_resource_report``), so remote node-hosts need no extra
connection; the dashboard serves the merged view at /api/node_stats.
"""

from __future__ import annotations

import os
import time
from typing import Dict


def collect_host_stats() -> Dict:
    """One sample of this host's physical state."""
    import psutil
    vm = psutil.virtual_memory()
    try:
        disk = psutil.disk_usage(os.sep)
        disk_row = {"total": disk.total, "used": disk.used,
                    "percent": disk.percent}
    except OSError:
        disk_row = {}
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = 0.0
    proc = psutil.Process()
    with proc.oneshot():
        proc_row = {
            "pid": proc.pid,
            "rss": proc.memory_info().rss,
            "num_threads": proc.num_threads(),
        }
    return {
        "ts": time.time(),
        "cpu_percent": psutil.cpu_percent(interval=None),
        "cpu_count": psutil.cpu_count(),
        "mem": {"total": vm.total, "available": vm.available,
                "percent": vm.percent},
        "disk": disk_row,
        "load_avg": [load1, load5, load15],
        "process": proc_row,
    }
