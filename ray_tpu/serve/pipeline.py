"""Serve deployment DAGs: author a multi-deployment inference graph,
build it into deployments + a runnable handle.

Parity: reference ``python/ray/serve/pipeline/`` (DAG authored with
``.bind()`` + ``InputNode``, compiled by ``pipeline.build`` into the
deployments it needs — ``api.py:8``, ``deployment_node.py``,
``deployment_method_node.py``, ``deployment_function_node.py``).

Authoring::

    @serve.deployment
    class Model:
        def __init__(self, weight): ...
        def forward(self, x): ...

    @serve.deployment
    def ensemble(a, b): ...

    with InputNode() as inp:
        m1 = Model.bind(1)
        m2 = Model.bind(2)
        dag = ensemble.bind(m1.forward.bind(inp), m2.forward.bind(inp))
    handle = pipeline.build(dag)     # deploys every node's deployment
    result = ray_tpu.get(handle.remote(5))

Execution walks the DAG per request: each bound method/function call
becomes a handle call on its deployment, upstream results resolved
first (fan-out stages run concurrently — sibling calls are submitted
before any result is awaited).
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


_uid_counter = itertools.count()


class DAGNode:
    """Base of the authoring nodes.  Every node gets a stable ``_uid``
    so a DAGHandle survives pickling (object ids do not)."""

    def __init__(self):
        self._uid = f"n{next(_uid_counter)}-{uuid.uuid4().hex[:8]}"

    def _resolve(self, input_value, cache: Dict[str, Any]):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the per-request input (reference InputNode).
    Usable as a context manager for authoring-scope clarity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, idx):
        return _InputAttr(self, idx)

    def _resolve(self, input_value, cache):
        return input_value


class _InputAttr(DAGNode):
    def __init__(self, parent: InputNode, idx):
        super().__init__()
        self._parent = parent
        self._idx = idx

    def _resolve(self, input_value, cache):
        return input_value[self._idx]


class ClassNode(DAGNode):
    """A deployment class bound to init args (``Deployment.bind``)."""

    def __init__(self, deployment, init_args: tuple,
                 init_kwargs: dict):
        super().__init__()
        self._deployment = deployment
        self._init_args = init_args
        self._init_kwargs = init_kwargs

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _MethodBinder(self, method_name)

    def _resolve(self, input_value, cache):
        raise TypeError(
            "a bound class is not callable in the DAG; bind one of its "
            "methods")


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "MethodNode":
        return MethodNode(self._class_node, self._method_name, args,
                          kwargs)


class MethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: tuple, kwargs: dict):
        super().__init__()
        self._class_node = class_node
        self._method = method_name
        self._args = args
        self._kwargs = kwargs

    def _resolve(self, input_value, cache):
        key = self._uid
        if key in cache:
            return cache[key]
        handle = cache["handles"][self._class_node._uid]
        # Upstream results pass as ObjectRefs: the replica call's arg
        # resolution awaits them, so every branch of the DAG is in
        # flight before anything blocks (true dataflow execution).
        args = [_submit(a, input_value, cache) for a in self._args]
        kwargs = {k: _submit(v, input_value, cache)
                  for k, v in self._kwargs.items()}
        ref = getattr(handle, self._method).remote(*args, **kwargs)
        cache[key] = ref
        return ref


class FunctionNode(DAGNode):
    """A function deployment bound to upstream nodes."""

    def __init__(self, deployment, args: tuple, kwargs: dict):
        super().__init__()
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs

    def _resolve(self, input_value, cache):
        key = self._uid
        if key in cache:
            return cache[key]
        handle = cache["handles"][self._uid]
        args = [_submit(a, input_value, cache) for a in self._args]
        kwargs = {k: _submit(v, input_value, cache)
                  for k, v in self._kwargs.items()}
        ref = handle.remote(*args, **kwargs)
        cache[key] = ref
        return ref


def _submit(node, input_value, cache):
    """Kick off a node (returns an ObjectRef for deployment calls, the
    literal value otherwise)."""
    if isinstance(node, DAGNode):
        return node._resolve(input_value, cache)
    return node


def _payload_nbytes(value) -> Optional[int]:
    """Cheap size of a bytes-like / buffer-backed payload (None when
    the size can't be known without serializing)."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    nbytes = getattr(value, "nbytes", None)  # numpy/jax arrays
    if isinstance(nbytes, int):
        return nbytes
    return None


def _has_input_attr(node, seen: Optional[set] = None) -> bool:
    """Whether any node indexes the request input driver-side
    (``InputNode()[i]``) — those DAGs need the literal value."""
    if seen is None:
        seen = set()
    if not isinstance(node, DAGNode) or node._uid in seen:
        return False
    seen.add(node._uid)
    if isinstance(node, _InputAttr):
        return True
    children = []
    if isinstance(node, MethodNode):
        children = list(node._args) + list(node._kwargs.values())
    elif isinstance(node, FunctionNode):
        children = list(node._args) + list(node._kwargs.values())
    return any(_has_input_attr(c, seen) for c in children)


class DAGHandle:
    """The built pipeline: ``remote(input)`` runs one request through
    the graph and returns a ref to the root's result.

    Zero-copy ingress: a large buffer-backed input is put ONCE into
    the object store and every stage receives the ObjectRef (the
    object-id handoff) — the payload materializes in each replica
    straight off the shm data plane instead of being pickled into
    every stage's task args (k stages = 1 serialization, not k).
    DAGs that index the input driver-side (``InputNode()[i]``) keep
    the literal value."""

    def __init__(self, root: DAGNode, handles: Dict[str, Any],
                 deployments: List):
        self._root = root
        self._handles = handles      # node uid -> DeploymentHandle
        self.deployments = deployments
        self._indexed_input = _has_input_attr(root)

    def remote(self, input_value=None):
        from ray_tpu._private.config import get_config
        from ray_tpu._private.object_ref import ObjectRef
        value = input_value
        if not self._indexed_input and \
                not isinstance(input_value, ObjectRef):
            threshold = get_config().serve_zero_copy_threshold_bytes
            nbytes = _payload_nbytes(input_value)
            if threshold >= 0 and nbytes is not None \
                    and nbytes >= threshold:
                value = ray_tpu.put(input_value)
        cache: Dict = {"handles": self._handles}
        out = self._root._resolve(value, cache)
        if isinstance(out, ObjectRef):
            return out
        return ray_tpu.put(out)


def _collect(node, class_nodes: List, fn_nodes: List, seen: set):
    if not isinstance(node, DAGNode) or node._uid in seen:
        return
    seen.add(node._uid)
    if isinstance(node, MethodNode):
        _collect(node._class_node, class_nodes, fn_nodes, seen)
        for a in list(node._args) + list(node._kwargs.values()):
            _collect(a, class_nodes, fn_nodes, seen)
    elif isinstance(node, FunctionNode):
        fn_nodes.append(node)
        for a in list(node._args) + list(node._kwargs.values()):
            _collect(a, class_nodes, fn_nodes, seen)
    elif isinstance(node, ClassNode):
        class_nodes.append(node)
        for a in (list(node._init_args) +
                  list(node._init_kwargs.values())):
            _collect(a, class_nodes, fn_nodes, seen)
    elif isinstance(node, _InputAttr):
        _collect(node._parent, class_nodes, fn_nodes, seen)


def _build_inner(root: DAGNode) -> DAGHandle:
    """Deploy every deployment the DAG references and return a runnable
    handle.

    Naming never mutates the author's nodes (a node reused across two
    builds keeps both DAGHandles working) and never collides with
    pre-existing standalone deployments."""
    from ray_tpu import serve
    class_nodes: List[ClassNode] = []
    fn_nodes: List[FunctionNode] = []
    _collect(root, class_nodes, fn_nodes, set())
    taken = set(serve.list_deployments())
    handles: Dict[int, Any] = {}
    deployments = []

    def fresh_name(base: str) -> str:
        name, n = base, 0
        while name in taken:
            n += 1
            name = f"{base}_{n}"
        taken.add(name)
        return name

    # Class deployments first: a FunctionNode/ClassNode may take a
    # bound class as an init/call arg (composition) — it resolves to
    # the already-deployed handle.
    def materialize_init_arg(a):
        if isinstance(a, ClassNode):
            return handles[a._uid]
        if isinstance(a, DAGNode):
            raise TypeError(
                "only bound classes (handles) and plain values may be "
                "used as deployment init args; request-time nodes "
                "cannot — they have no value at deploy time")
        return a

    def deploy_node(node):
        name = fresh_name(node._deployment.name)
        d = node._deployment.options(name=name, route_prefix=None)
        if isinstance(node, ClassNode):
            d.deploy(*[materialize_init_arg(a)
                       for a in node._init_args],
                     **{k: materialize_init_arg(v)
                        for k, v in node._init_kwargs.items()})
        else:
            d.deploy()
        deployments.append(d)
        handles[node._uid] = serve.get_deployment(name).get_handle()

    # Composition means a ClassNode's init args may reference other
    # ClassNodes: deploy in dependency order.
    pending = list(class_nodes)
    while pending:
        progressed = False
        for node in list(pending):
            deps = [a for a in (list(node._init_args) +
                                list(node._init_kwargs.values()))
                    if isinstance(a, ClassNode)]
            if all(dep._uid in handles for dep in deps):
                deploy_node(node)
                pending.remove(node)
                progressed = True
        if not progressed:
            raise ValueError("cycle in deployment init-arg bindings")
    for node in fn_nodes:
        deploy_node(node)
    return DAGHandle(root, handles, deployments)


class PipelineDriver:
    """Ingress deployment wrapping a DAGHandle: HTTP requests (and
    handle calls) run the graph (reference DAGDriver shape).  The
    DAGHandle pickles into the replica — DeploymentHandles reconstruct
    from their names, node identity is uid-stable."""

    def __init__(self, dag_handle: "DAGHandle"):
        self._dag = dag_handle

    def __call__(self, request):
        # HTTP path: the proxy passes an HTTPRequest; json body (or
        # query "input") is the DAG input.  Direct handle calls pass
        # the input value through unchanged.
        value = request
        body = getattr(request, "json", None)
        if callable(body):
            try:
                value = body()
            except Exception:
                # No/invalid JSON body: the documented fallback is the
                # "input" query param, not the raw query_params dict.
                value = getattr(request, "query_params", {}).get("input")
        return ray_tpu.get(self._dag.remote(value))


def build(root: DAGNode, http_route: Optional[str] = None):
    """Deploy every deployment the DAG references and return a runnable
    handle (reference ``pipeline.build``, api.py:8); with
    ``http_route``, additionally deploy a :class:`PipelineDriver`
    ingress bound to that route and return it as
    ``handle.ingress``."""
    handle = _build_inner(root)
    handle.ingress = None
    if http_route is not None:
        from ray_tpu import serve
        driver = serve.deployment(
            PipelineDriver,
            name=f"pipeline_driver{http_route.replace('/', '_')}",
            route_prefix=http_route)
        driver.deploy(handle)
        handle.ingress = driver
    return handle
