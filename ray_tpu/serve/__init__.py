"""ray_tpu.serve: model serving on the actor runtime.

Parity: reference ``python/ray/serve/`` — detached ``ServeController``
holding goal state (controller.py:39), ``DeploymentState`` reconciler
scaling replica actors (deployment_state.py), ``Router`` with
round-robin + backpressure (router.py:170), ``@serve.deployment`` API
(api.py:1032), ``@serve.batch`` batching (batching.py), long-poll config
push (reference long_poll.py; here ``ServeController.listen_for_change``),
queue-metric autoscaling (autoscaling_policy.py), HTTP proxy
(reference http_proxy.py; stdlib ThreadingHTTPServer in our
``serve/http_proxy.py``).
"""

from ray_tpu.serve.api import (  # noqa: F401
    Deployment, delete, deployment, get_deployment, list_deployments, run,
    shutdown, start)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle  # noqa: F401
from ray_tpu.serve.http_proxy import HTTPRequest  # noqa: F401
from ray_tpu.serve import pipeline  # noqa: F401
from ray_tpu.serve.pipeline import InputNode  # noqa: F401

__all__ = ["Deployment", "DeploymentHandle", "HTTPRequest", "batch",
           "pipeline", "InputNode",
           "delete", "deployment", "get_deployment", "list_deployments",
           "run", "shutdown", "start"]
