"""ServeController: the serve control plane actor.

Parity: reference ``python/ray/serve/controller.py`` (:39
``ServeController``) + ``deployment_state.py`` (:45,602 reconciler) —
goal state per deployment (replica count, config), reconcile loop
creating/stopping replica actors, long-poll change notifications
(long_poll.py), queue-metric autoscaling (autoscaling_policy.py:
scale to ceil(total_queued / target_num_ongoing_requests_per_replica)
clamped to [min,max]).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"


class DeploymentInfo:
    def __init__(self, name: str, serialized_init, num_replicas: int,
                 ray_actor_options: Optional[dict] = None,
                 max_concurrent_queries: int = 100,
                 autoscaling_config: Optional[dict] = None,
                 route_prefix: Optional[str] = None,
                 version: int = 0):
        self.name = name
        self.serialized_init = serialized_init
        self.num_replicas = num_replicas
        self.ray_actor_options = dict(ray_actor_options or {})
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.route_prefix = route_prefix
        self.version = version


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List] = {}   # name -> actor handles
        self._config_version = 0
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._shutdown = False
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # ---- API (called from serve.api) ----------------------------------
    def deploy(self, name: str, serialized_init, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               max_concurrent_queries: int = 100,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = None) -> bool:
        with self._lock:
            if route_prefix is not None:
                for other, info in self._deployments.items():
                    if other != name and info.route_prefix == route_prefix:
                        raise ValueError(
                            f"route_prefix {route_prefix!r} is already "
                            f"used by deployment {other!r}")
            prev = self._deployments.get(name)
            version = (prev.version + 1) if prev else 0
            self._deployments[name] = DeploymentInfo(
                name, serialized_init, num_replicas, ray_actor_options,
                max_concurrent_queries, autoscaling_config, route_prefix,
                version)
            if prev is not None:
                # Code/config changed: replace existing replicas.
                self._stop_replicas(name, len(self._replicas.get(name, [])))
            self._cv.notify_all()
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            if name not in self._deployments:
                return False
            del self._deployments[name]
            self._stop_replicas(name, len(self._replicas.get(name, [])))
            self._replicas.pop(name, None)
            self._bump()
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            return {"name": info.name, "num_replicas": info.num_replicas,
                    "version": info.version,
                    "num_running_replicas":
                        len(self._replicas.get(name, []))}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def get_deployment_spec(self, name: str):
        """(serialized_init, config dict) for rebuilding a Deployment
        (serve.get_deployment parity)."""
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            return (info.serialized_init, {
                "num_replicas": info.num_replicas,
                "ray_actor_options": info.ray_actor_options,
                "max_concurrent_queries": info.max_concurrent_queries,
                "autoscaling_config": info.autoscaling_config,
                "route_prefix": info.route_prefix,
            })

    def get_route_table(self) -> Dict[str, str]:
        """route_prefix -> deployment name (http_proxy route updates)."""
        with self._lock:
            return {info.route_prefix: name
                    for name, info in self._deployments.items()
                    if info.route_prefix}

    def get_replica_handles(self, name: str) -> List:
        with self._lock:
            return list(self._replicas.get(name, []))

    # ---- long poll (reference long_poll.py) ---------------------------
    def listen_for_change(self, known_version: int, timeout: float = 10.0
                          ) -> int:
        """Blocks until the routing config version advances past
        ``known_version`` (or timeout); returns the current version."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._config_version <= known_version and \
                    not self._shutdown:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            return self._config_version

    def _bump(self):
        self._config_version += 1
        self._cv.notify_all()

    # ---- reconciliation ------------------------------------------------
    def _target_replicas(self, info: DeploymentInfo) -> int:
        cfg = info.autoscaling_config
        if not cfg:
            return info.num_replicas
        import math
        handles = self._replicas.get(info.name, [])
        if not handles:
            return max(1, cfg.get("min_replicas", 1))
        try:
            inflight = sum(ray_tpu.get(
                [h.get_num_inflight.remote() for h in handles]))
        except Exception:
            return len(handles)
        target_per = cfg.get("target_num_ongoing_requests_per_replica", 1)
        want = math.ceil(inflight / max(target_per, 1e-9)) if inflight \
            else cfg.get("min_replicas", 1)
        return max(cfg.get("min_replicas", 1),
                   min(cfg.get("max_replicas", 10), want))

    def _reconcile_once(self):
        from ray_tpu.serve.replica import ReplicaActor
        with self._lock:
            if self._shutdown:
                return
            work = []
            for name, info in self._deployments.items():
                have = self._replicas.setdefault(name, [])
                want = self._target_replicas(info)
                if len(have) < want:
                    work.append((name, info, want - len(have)))
                elif len(have) > want:
                    self._stop_replicas(name, len(have) - want)
                    self._bump()
            deployments = dict(self._deployments)
        changed = False
        for name, info, count in work:
            opts = dict(info.ray_actor_options)
            opts.setdefault("num_cpus", 1)
            # +2 headroom so control calls (get_num_inflight, health) never
            # queue behind saturated request slots — the router, not actor
            # concurrency, enforces max_concurrent_queries.
            opts["max_concurrency"] = max(2, info.max_concurrent_queries) + 2
            cls = ray_tpu.remote(**opts)(ReplicaActor)
            new = [cls.remote(info.serialized_init) for _ in range(count)]
            with self._lock:
                if name in self._deployments and \
                        self._deployments[name].version == info.version:
                    self._replicas[name].extend(new)
                    changed = True
                else:
                    for h in new:
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            pass
        if changed:
            with self._lock:
                self._bump()

    def _stop_replicas(self, name: str, count: int):
        # Must hold lock.
        handles = self._replicas.get(name, [])
        victims, self._replicas[name] = handles[:count], handles[count:]
        for h in victims:
            try:
                ray_tpu.kill(h)
            except Exception:
                pass

    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception:
                pass
            time.sleep(0.25)

    def shutdown(self) -> bool:
        with self._lock:
            self._shutdown = True
            for name in list(self._deployments):
                self._stop_replicas(name,
                                    len(self._replicas.get(name, [])))
            self._deployments.clear()
            self._cv.notify_all()
        return True
