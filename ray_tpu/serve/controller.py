"""ServeController: the serve control plane actor.

Parity: reference ``python/ray/serve/controller.py`` (:39
``ServeController``) + ``deployment_state.py`` (:45,602 reconciler) —
goal state per deployment (replica count, config), reconcile loop
creating/stopping replica actors, long-poll change notifications
(long_poll.py), queue-metric autoscaling (autoscaling_policy.py:
scale to ceil(total_queued / target_num_ongoing_requests_per_replica)
clamped to [min,max]).

Autoscaling (this repo's serving-under-load lever): the queue-depth
signal is the sum of replica in-flight counts (probed) and router
queue reports (:meth:`report_router_queue` — callers parked waiting
for a free replica slot).  The signal is EWMA-smoothed and the policy
has hysteresis: a scale decision fires only after the pressure
persists past ``upscale_delay_s`` / ``downscale_delay_s`` (reference
``autoscaling_policy.py`` delay semantics), so a one-tick burst never
churns replicas.  New replicas are PLACED through the pack-mode
kernel solve (``resource_demand_scheduler._pack_mode_solve`` — the
same device-resident path tasks and placement groups ride) and pinned
with soft node affinity; the solve is gated by
``serve_kernel_placement`` and falls back to DEFAULT placement on any
failure.  Decision series are exported at /metrics
(``ray_tpu_serve_autoscaler_*``).

Updates are *rolling* (reference ``deployment_state.py`` version-aware
reconciler): a redeploy that changes code/config marks live replicas as
old-version; the reconciler surges new-version replicas in, waits for
them to pass ``check_health``, then retires the same number of
old-version ones — serving capacity never drops below the target.  A
redeploy that changes only ``user_config`` skips restarts entirely and
calls ``reconfigure`` on the live replicas in place (reference
``deployment_state.py`` lightweight-update path).  The reconciler also
runs periodic health checks and replaces replicas that fail them
(reference ``replica.py`` health-check loop).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.debug import swallow
from ray_tpu._private.debug.lock_order import (diag_condition, diag_lock,
                                               diag_rlock)

CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"

# Fraction of the target replica count a rolling update may surge above
# it while replacing old-version replicas (reference max_surge semantics).
_ROLLING_SURGE_FRACTION = 0.25
_HEALTH_CHECK_PERIOD_S = 2.5
_HEALTH_CHECK_FAILURE_THRESHOLD = 2
_RECONCILE_PERIOD_S = 0.25
# Router queue reports older than this are ignored when aggregating the
# queue-depth signal (a stopped router must not pin its last depth).
_ROUTER_REPORT_TTL_S = 2.0
# EWMA smoothing for the load signal (per reconcile tick).
_LOAD_EWMA_ALPHA = 0.5
# Hysteresis defaults when autoscaling_config doesn't set them.
_UPSCALE_DELAY_S = 0.3
_DOWNSCALE_DELAY_S = 2.0


class DeploymentInfo:
    def __init__(self, name: str, serialized_init, num_replicas: int,
                 ray_actor_options: Optional[dict] = None,
                 max_concurrent_queries: int = 100,
                 autoscaling_config: Optional[dict] = None,
                 route_prefix: Optional[str] = None,
                 version: int = 0):
        self.name = name
        self.serialized_init = serialized_init
        self.num_replicas = num_replicas
        self.ray_actor_options = dict(ray_actor_options or {})
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.route_prefix = route_prefix
        self.version = version

    def replica_fingerprint(self) -> tuple:
        """Everything that forces a replica restart when it changes —
        the deployment body and actor options, but NOT user_config
        (which reconfigures in place)."""
        deployment_def, init_args, init_kwargs, _user_config = \
            self.serialized_init
        return (deployment_def, init_args, init_kwargs,
                tuple(sorted(self.ray_actor_options.items())),
                self.max_concurrent_queries)


class _Replica:
    """A live replica actor and the deployment version it was built at."""

    __slots__ = ("handle", "version")

    def __init__(self, handle, version: int):
        self.handle = handle
        self.version = version


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List[_Replica]] = {}
        self._config_version = 0
        self._lock = diag_rlock("serve.ServeController._lock")
        self._cv = diag_condition(self._lock,
                                  name="serve.ServeController._cv")
        # Serializes whole reconcile passes (deploy handler vs loop):
        # replica startup blocks on health checks, so two concurrent
        # passes would both see the same deficit and double-start.
        self._reconcile_mutex = diag_lock(
            "serve.ServeController._reconcile_mutex")
        self._shutdown = False
        self._last_health_check = 0.0
        self._health_fail_counts: Dict[_Replica, int] = {}
        # Autoscaler state: router queue reports (deployment ->
        # router_id -> (queued, ts)), EWMA-smoothed load, and the
        # hysteresis timestamps (when the scale condition FIRST held).
        self._router_queues: Dict[str, Dict[str, Tuple[int, float]]] = {}
        self._load_ewma: Dict[str, float] = {}
        self._scale_up_since: Dict[str, float] = {}
        self._scale_down_since: Dict[str, float] = {}
        self.autoscaler_stats = {"scale_ups": 0, "scale_downs": 0,
                                 "kernel_placements": 0,
                                 "kernel_fallbacks": 0}
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # ---- API (called from serve.api) ----------------------------------
    def deploy(self, name: str, serialized_init, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               max_concurrent_queries: int = 100,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = None) -> bool:
        with self._lock:
            if route_prefix is not None:
                for other, info in self._deployments.items():
                    if other != name and info.route_prefix == route_prefix:
                        raise ValueError(
                            f"route_prefix {route_prefix!r} is already "
                            f"used by deployment {other!r}")
            prev = self._deployments.get(name)
            version = (prev.version + 1) if prev else 0
            info = DeploymentInfo(
                name, serialized_init, num_replicas, ray_actor_options,
                max_concurrent_queries, autoscaling_config, route_prefix,
                version)
            self._deployments[name] = info
            lightweight = (
                prev is not None
                and prev.replica_fingerprint() == info.replica_fingerprint())
            replicas = list(self._replicas.get(name, ()))
            self._cv.notify_all()
        if lightweight:
            # Only user_config (or replica count / autoscaling / route)
            # changed: reconfigure live replicas in place, no restarts.
            # Under the reconcile mutex so two concurrent deploys can't
            # interleave their reconfigure waves out of order.  A
            # replica is version-bumped only AFTER its reconfigure
            # succeeds — on failure (rejected config, dead actor) it
            # stays old-version and the rolling reconciler replaces it
            # with a fresh replica built from the new serialized_init.
            user_config = serialized_init[3]
            with self._reconcile_mutex:
                # A later deploy may have won the mutex first: applying
                # this (older) wave would regress replicas to a stale
                # config, so skip it entirely.
                with self._lock:
                    cur = self._deployments.get(name)
                    stale = cur is None or cur.version != version
                if not stale:
                    # All reconfigures issued up front, gathered under
                    # one shared deadline — N hung replicas cost one
                    # timeout, not N, and we hold _reconcile_mutex here.
                    waves = [(rep,
                              rep.handle.reconfigure.remote(user_config)
                              if user_config is not None else None)
                             for rep in replicas]
                    deadline = time.monotonic() + 30.0
                    for rep, fut in waves:
                        try:
                            if fut is not None:
                                ray_tpu.get(fut, timeout=max(
                                    0.1, deadline - time.monotonic()))
                            rep.version = version
                        except Exception as e:
                            # Rejected config / hung or dead replica:
                            # stays old-version; the rolling reconciler
                            # replaces it with a fresh replica.
                            swallow.noted("serve.controller.reconfigure", e)
            with self._lock:
                self._bump()
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            if name not in self._deployments:
                return False
            del self._deployments[name]
            self._stop_replicas(name, len(self._replicas.get(name, [])))
            self._replicas.pop(name, None)
            self._router_queues.pop(name, None)
            self._load_ewma.pop(name, None)
            self._scale_up_since.pop(name, None)
            self._scale_down_since.pop(name, None)
            self._bump()
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            reps = self._replicas.get(name, [])
            return {"name": info.name, "num_replicas": info.num_replicas,
                    "version": info.version,
                    "num_running_replicas": len(reps),
                    "num_current_version_replicas":
                        sum(1 for r in reps if r.version == info.version)}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def get_deployment_spec(self, name: str):
        """(serialized_init, config dict) for rebuilding a Deployment
        (serve.get_deployment parity)."""
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            return (info.serialized_init, {
                "num_replicas": info.num_replicas,
                "ray_actor_options": info.ray_actor_options,
                "max_concurrent_queries": info.max_concurrent_queries,
                "autoscaling_config": info.autoscaling_config,
                "route_prefix": info.route_prefix,
            })

    def get_route_table(self) -> Dict[str, str]:
        """route_prefix -> deployment name (http_proxy route updates)."""
        with self._lock:
            return {info.route_prefix: name
                    for name, info in self._deployments.items()
                    if info.route_prefix}

    def get_replica_handles(self, name: str) -> List:
        # Old-version replicas keep serving until the rolling update
        # retires them, so the router sees all of them.
        with self._lock:
            return [r.handle for r in self._replicas.get(name, [])]

    def get_autoscaler_stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.autoscaler_stats)

    def report_router_queue(self, name: str, router_id: str,
                            queued: int) -> bool:
        """Router queue-depth report (callers parked in assign_request)
        — one half of the autoscaler's queue-depth signal."""
        with self._lock:
            self._router_queues.setdefault(name, {})[router_id] = (
                int(queued), time.monotonic())
        return True

    def _router_queue_depth(self, name: str) -> int:
        """Aggregate live router reports for a deployment; stale
        reports (router stopped or wedged) age out after the TTL."""
        now = time.monotonic()
        reports = self._router_queues.get(name)
        if not reports:
            return 0
        total = 0
        for rid, (queued, ts) in list(reports.items()):
            if now - ts > _ROUTER_REPORT_TTL_S:
                del reports[rid]
            else:
                total += queued
        return total

    # ---- long poll (reference long_poll.py) ---------------------------
    def listen_for_change(self, known_version: int, timeout: float = 10.0
                          ) -> int:
        """Blocks until the routing config version advances past
        ``known_version`` (or timeout); returns the current version."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._config_version <= known_version and \
                    not self._shutdown:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            return self._config_version

    def _bump(self):
        self._config_version += 1
        self._cv.notify_all()

    # ---- reconciliation ------------------------------------------------
    def _probe_inflight(self) -> Dict[str, Optional[int]]:
        """Queue-depth probes for autoscaled deployments, issued OUTSIDE
        ``self._lock`` — a slow replica must stall only the reconcile
        loop, never deploy/get_deployment_info/long-poll entry points.
        None = probe failed (caller keeps the current replica count)."""
        with self._lock:
            targets = {
                name: [r.handle for r in self._replicas.get(name, [])]
                for name, info in self._deployments.items()
                if info.autoscaling_config}
        # All probes issued up front against ONE shared deadline, so N
        # deployments with hung replicas cost one timeout, not N
        # (same shape as _maybe_health_check).
        futures = {name: [h.get_num_inflight.remote() for h in handles]
                   for name, handles in targets.items() if handles}
        deadline = time.monotonic() + 5.0
        out: Dict[str, Optional[int]] = {}
        for name, futs in futures.items():
            try:
                out[name] = sum(ray_tpu.get(
                    futs, timeout=max(0.1, deadline - time.monotonic())))
            except Exception:
                out[name] = None
        return out

    def _target_replicas(self, info: DeploymentInfo,
                         probed: Optional[int] = None) -> int:
        """Queue-depth autoscaling policy with hysteresis.

        load = replica in-flight (probed) + router queue depth, EWMA
        smoothed; desired = ceil(load / target_per_replica) clamped to
        [min, max].  A scale decision fires only after the desire has
        persisted past upscale_delay_s / downscale_delay_s — pressure
        must hold, not spike."""
        cfg = info.autoscaling_config
        if not cfg:
            return info.num_replicas
        name = info.name
        n_current = len(self._replicas.get(name, []))
        if not n_current:
            return max(1, cfg.get("min_replicas", 1))
        if probed is None:
            return n_current      # probe failed: hold steady
        load = float(probed + self._router_queue_depth(name))
        prev = self._load_ewma.get(name)
        ewma = load if prev is None else (
            _LOAD_EWMA_ALPHA * load + (1 - _LOAD_EWMA_ALPHA) * prev)
        self._load_ewma[name] = ewma
        target_per = cfg.get("target_num_ongoing_requests_per_replica", 1)
        want = math.ceil(ewma / max(target_per, 1e-9)) if ewma > 1e-9 \
            else cfg.get("min_replicas", 1)
        want = max(cfg.get("min_replicas", 1),
                   min(cfg.get("max_replicas", 10), want))
        now = time.monotonic()
        decided = n_current
        if want > n_current:
            self._scale_down_since.pop(name, None)
            since = self._scale_up_since.setdefault(name, now)
            if now - since >= cfg.get("upscale_delay_s",
                                      _UPSCALE_DELAY_S):
                self._scale_up_since.pop(name, None)
                self.autoscaler_stats["scale_ups"] += 1
                decided = want
        elif want < n_current:
            self._scale_up_since.pop(name, None)
            since = self._scale_down_since.setdefault(name, now)
            if now - since >= cfg.get("downscale_delay_s",
                                      _DOWNSCALE_DELAY_S):
                self._scale_down_since.pop(name, None)
                self.autoscaler_stats["scale_downs"] += 1
                decided = want
        else:
            self._scale_up_since.pop(name, None)
            self._scale_down_since.pop(name, None)
        self._observe_autoscaler(name, ewma, want, n_current, decided)
        return decided

    def _observe_autoscaler(self, name: str, load: float, want: int,
                            current: int, decided: int) -> None:
        """Autoscaler decision series at /metrics: smoothed load,
        desired vs running replicas, and a decision counter when a
        scale actually fires."""
        try:
            from ray_tpu._private.metrics_agent import get_metrics_registry
            reg = get_metrics_registry()
            labels = (("deployment", name),)
            reg.register("ray_tpu_serve_autoscaler_load", "gauge")
            reg.set("ray_tpu_serve_autoscaler_load", load, labels)
            reg.register("ray_tpu_serve_autoscaler_desired", "gauge")
            reg.set("ray_tpu_serve_autoscaler_desired", float(want), labels)
            reg.register("ray_tpu_serve_replicas", "gauge")
            reg.set("ray_tpu_serve_replicas", float(current), labels)
            if decided != current:
                reg.register("ray_tpu_serve_autoscaler_decisions", "counter")
                reg.inc("ray_tpu_serve_autoscaler_decisions", 1.0,
                        (("deployment", name),
                         ("direction",
                          "up" if decided > current else "down")))
        except Exception as e:
            swallow.noted("serve.controller.autoscaler_metrics", e)

    def _kernel_place(self, opts: dict, count: int) -> List[Optional[Any]]:
        """Place ``count`` identical replicas through the pack-mode
        kernel solve: snapshot the cluster's dense availability view,
        solve replica-demand x nodes on device, and return one node id
        per replica (None = no affinity, DEFAULT placement).  Gated by
        ``serve_kernel_placement``; any failure falls back to DEFAULT
        — placement is an optimization, never a liveness dependency."""
        cfg = get_config()
        mode = cfg.serve_kernel_placement
        if mode == "off":
            return [None] * count
        try:
            from ray_tpu._private import worker as worker_mod
            from ray_tpu.autoscaler.resource_demand_scheduler import (
                _pack_mode_matrices, _pack_mode_solve)
            w = worker_mod.global_worker()
            view = w.cluster.gcs.resource_manager.view
            node_ids, _total, avail, columns = view.snapshot()
            if not node_ids or (mode == "auto"
                                and len(node_ids) < cfg.serve_kernel_min_nodes):
                return [None] * count
            inv = {i: name for name, i in columns.items()}
            node_res = [{inv[j]: float(avail[r, j])
                         for j in range(avail.shape[1]) if avail[r, j] > 0}
                        for r in range(len(node_ids))]
            demand = dict(opts.get("resources") or {})
            demand["CPU"] = float(opts.get("num_cpus", 1) or 0)
            if opts.get("num_gpus"):
                demand["GPU"] = float(opts["num_gpus"])
            demand = {k: v for k, v in demand.items() if v > 0}
            if not demand:
                return [None] * count
            names, runs, dem, counts, avail_m = _pack_mode_matrices(
                node_res, [demand] * count)
            _unfulfilled, alloc = _pack_mode_solve(runs, dem, counts,
                                                   avail_m)
            placements: List[Optional[Any]] = []
            for ci in range(alloc.shape[0]):
                for ni in range(alloc.shape[1]):
                    placements.extend([node_ids[ni]] *
                                      int(alloc[ci, ni]))
            placements = placements[:count]
            self.autoscaler_stats["kernel_placements"] += len(placements)
            # Replicas the solve couldn't fit anywhere fall back to
            # DEFAULT placement (soft affinity would lie about intent).
            placements.extend([None] * (count - len(placements)))
            return placements
        except Exception as e:
            self.autoscaler_stats["kernel_fallbacks"] += 1
            swallow.noted("serve.controller.kernel_place", e)
            return [None] * count

    @staticmethod
    def _weight_object_ids(info: DeploymentInfo) -> List:
        """Object ids of ObjectRef init args (deployed model weights)."""
        from ray_tpu._private.object_ref import ObjectRef
        _def, init_args, init_kwargs, _cfg = info.serialized_init
        return [a.object_id()
                for a in list(init_args or ()) +
                list((init_kwargs or {}).values())
                if isinstance(a, ObjectRef)]

    @staticmethod
    def _stagger_weight_pull(oids: List, baselines: Dict,
                             timeout: float = 2.0) -> None:
        """Cold-start relay shaping: before creating the NEXT replica,
        wait until a weight object has grown a new directory row —
        the predecessor's pull is in flight (partial row) or done, so
        the successor's pull chains off it (transfer.relay) instead of
        opening another full origin stream.  Best-effort: on timeout
        (e.g. the predecessor landed on the origin's node and never
        pulled) the start proceeds."""
        try:
            from ray_tpu._private import worker as worker_mod
            directory = worker_mod.global_worker().cluster.object_directory
        except Exception as e:
            swallow.noted("serve.controller.stagger_directory", e)
            return
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                for oid in oids:
                    if len(directory.get_candidates(oid)) > \
                            baselines.get(oid, 1):
                        return
            except Exception as e:
                swallow.noted("serve.controller.stagger_probe", e)
                return
            time.sleep(0.01)

    def _start_replicas(self, info: DeploymentInfo, count: int
                        ) -> List[_Replica]:
        from ray_tpu.serve.replica import ReplicaActor
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        opts = dict(info.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        # +2 headroom so control calls (get_num_inflight, health) never
        # queue behind saturated request slots — the router, not actor
        # concurrency, enforces max_concurrent_queries.
        opts["max_concurrency"] = max(2, info.max_concurrent_queries) + 2
        weight_oids = self._weight_object_ids(info) if count > 1 else []
        baselines: Dict = {}
        if weight_oids:
            try:
                from ray_tpu._private import worker as worker_mod
                directory = \
                    worker_mod.global_worker().cluster.object_directory
                baselines = {oid: len(directory.get_candidates(oid))
                             for oid in weight_oids}
            except Exception as e:
                swallow.noted("serve.controller.stagger_baseline", e)
                weight_oids = []
        placements = self._kernel_place(opts, count)
        new = []
        for i, node_id in enumerate(placements):
            rep_opts = dict(opts)
            if node_id is not None:
                # Soft: the kernel's pick is a preference, not a cage —
                # if the node filled up since the snapshot the scheduler
                # may still place elsewhere.
                rep_opts["scheduling_strategy"] = \
                    NodeAffinitySchedulingStrategy(node_id, soft=True)
            cls = ray_tpu.remote(**rep_opts)(ReplicaActor)
            new.append(_Replica(
                cls.remote(info.serialized_init,
                           deployment_name=info.name), info.version))
            if weight_oids and i == 0:
                # Only the FIRST gap needs the wait: once one transfer
                # is in flight (or one extra copy exists), every later
                # pull has a non-origin source to chain from.
                self._stagger_weight_pull(weight_oids, baselines)
        return new

    def _adopt_or_kill(self, name: str, version: int,
                       new: List[_Replica]) -> bool:
        """Register freshly started replicas iff the deployment still
        wants that version; otherwise kill them.  Returns adopted?"""
        with self._lock:
            info = self._deployments.get(name)
            if info is not None and info.version == version:
                self._replicas.setdefault(name, []).extend(new)
                return True
        for rep in new:
            try:
                ray_tpu.kill(rep.handle)
            except Exception as e:
                swallow.noted("serve.controller.kill_unadopted", e)
        return False

    def _wait_healthy(self, reps: List[_Replica], timeout: float = 30.0
                      ) -> List[_Replica]:
        """Block until each replica answers check_health (actor started
        and ctor ran); drop ones that error out.  All probes are issued
        up front so N hung replicas cost one timeout, not N."""
        futs = [(rep, rep.handle.check_health.remote()) for rep in reps]
        healthy = []
        deadline = time.monotonic() + timeout
        for rep, fut in futs:
            try:
                ray_tpu.get(fut, timeout=max(
                    0.1, deadline - time.monotonic()))
                healthy.append(rep)
            except Exception as e:
                swallow.noted("serve.controller.unhealthy_start", e)
                try:
                    ray_tpu.kill(rep.handle)
                except Exception as e2:
                    swallow.noted("serve.controller.kill_unhealthy", e2)
        return healthy

    def _drain_and_kill(self, victims: List[_Replica],
                        drain_timeout: float = 10.0):
        """Retire replicas gracefully: they are already out of
        _replicas and the config version was bumped, so routers drop
        them on their next long-poll refresh; wait for in-flight
        requests (and the router refresh window) to drain before
        killing (reference replica graceful_shutdown loop)."""
        if not victims:
            return
        # Grace so routers' long-polls (woken by the bump) refetch the
        # replica set before we start judging in-flight counts.
        time.sleep(0.25)
        deadline = time.monotonic() + drain_timeout
        pending = list(victims)
        while pending and time.monotonic() < deadline:
            still = []
            for rep in pending:
                try:
                    if ray_tpu.get(rep.handle.get_num_inflight.remote(),
                                   timeout=2.0) > 0:
                        still.append(rep)
                except exceptions.GetTimeoutError:
                    # Slow to answer != dead: keep draining it until
                    # the overall deadline.
                    still.append(rep)
                except Exception as e:
                    # Dead already — nothing to drain.
                    swallow.noted("serve.controller.drain_probe", e)
            pending = still
            if pending:
                time.sleep(0.05)
        # Last service before the kill: let each replica fail its
        # parked @serve.batch requests loudly (callers otherwise hit
        # their 60s event-wait cap).  Fire-and-forget with a short
        # gather — a dead replica just errors the ref.
        shutdown_futs = []
        for rep in victims:
            try:
                shutdown_futs.append(rep.handle.prepare_shutdown.remote())
            except Exception as e:
                swallow.noted("serve.controller.prepare_shutdown", e)
        deadline = time.monotonic() + 2.0
        for fut in shutdown_futs:
            try:
                ray_tpu.get(fut, timeout=max(
                    0.1, deadline - time.monotonic()))
            except Exception as e:
                swallow.noted("serve.controller.prepare_shutdown", e)
        for rep in victims:
            self._health_fail_counts.pop(rep, None)
            try:
                ray_tpu.kill(rep.handle)
            except Exception as e:
                swallow.noted("serve.controller.kill_retired", e)

    def _reconcile_once(self):
        with self._reconcile_mutex:
            self._reconcile_locked()

    def _reconcile_locked(self):
        probes = self._probe_inflight()    # blocking gets, lock NOT held
        with self._lock:
            if self._shutdown:
                return
            scale_up: List[Tuple[str, DeploymentInfo, int]] = []
            rolling: List[Tuple[str, DeploymentInfo, int]] = []
            retire: List[_Replica] = []
            for name, info in self._deployments.items():
                reps = self._replicas.setdefault(name, [])
                want = self._target_replicas(info, probes.get(name))
                old = [r for r in reps if r.version != info.version]
                if len(reps) < want:
                    scale_up.append((name, info, want - len(reps)))
                elif len(reps) > want:
                    # Retire old-version replicas first when shrinking.
                    reps.sort(key=lambda r: r.version == info.version)
                    n_drop = len(reps) - want
                    retire.extend(reps[:n_drop])
                    self._replicas[name] = reps[n_drop:]
                    old = [r for r in self._replicas[name]
                           if r.version != info.version]
                if old:
                    surge = max(1, math.ceil(want * _ROLLING_SURGE_FRACTION))
                    rolling.append((name, info, min(surge, len(old))))
            if retire:
                self._bump()
        self._drain_and_kill(retire)
        for name, info, count in scale_up:
            new = self._wait_healthy(self._start_replicas(info, count))
            if new and self._adopt_or_kill(name, info.version, new):
                with self._lock:
                    self._bump()
        for name, info, count in rolling:
            # Surge `count` new-version replicas in, wait until they are
            # serving, then retire `count` old-version ones.
            new = self._wait_healthy(self._start_replicas(info, count))
            if not new:
                continue
            if not self._adopt_or_kill(name, info.version, new):
                continue
            with self._lock:
                reps = self._replicas.get(name, [])
                old = [r for r in reps if r.version != info.version]
                victims = old[:len(new)]
                self._replicas[name] = [r for r in reps
                                        if r not in victims]
                self._bump()
            self._drain_and_kill(victims)
        self._maybe_health_check()

    def _maybe_health_check(self):
        now = time.monotonic()
        if now - self._last_health_check < _HEALTH_CHECK_PERIOD_S:
            return
        self._last_health_check = now
        with self._lock:
            snapshot = {name: list(reps)
                        for name, reps in self._replicas.items()}
        # All probes issued up front against ONE shared deadline, so N
        # hung replicas cost one timeout — and this runs under the
        # reconcile mutex, where a long stall would block deploys.
        probes = [(name, rep, rep.handle.check_health.remote())
                  for name, reps in snapshot.items() for rep in reps]
        deadline = time.monotonic() + 10.0
        dead: List[Tuple[str, _Replica]] = []
        for name, rep, fut in probes:
            try:
                ray_tpu.get(fut, timeout=max(
                    0.1, deadline - time.monotonic()))
                self._health_fail_counts.pop(rep, None)
            except exceptions.GetTimeoutError:
                # Slow answers only count toward a consecutive-failure
                # threshold (reference health loop): one long GC pause
                # or load spike is not death.
                fails = self._health_fail_counts.get(rep, 0) + 1
                self._health_fail_counts[rep] = fails
                if fails >= _HEALTH_CHECK_FAILURE_THRESHOLD:
                    dead.append((name, rep))
            except Exception:
                # The probe itself failed (actor died, user
                # check_health raised): definitively unhealthy.
                dead.append((name, rep))
        if not dead:
            return
        with self._lock:
            for name, rep in dead:
                reps = self._replicas.get(name)
                if reps and rep in reps:
                    reps.remove(rep)
                self._health_fail_counts.pop(rep, None)
            self._bump()
        # Drain whatever is still answering before the kill; a truly
        # dead replica drains instantly (its probe raises non-timeout).
        self._drain_and_kill([rep for _, rep in dead], drain_timeout=5.0)
        # The next reconcile pass scales the deployment back up.

    def _stop_replicas(self, name: str, count: int):
        # Must hold lock.
        reps = self._replicas.get(name, [])
        victims, self._replicas[name] = reps[:count], reps[count:]
        for rep in victims:
            self._health_fail_counts.pop(rep, None)
            try:
                ray_tpu.kill(rep.handle)
            except Exception as e:
                swallow.noted("serve.controller.kill_stopped", e)

    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception as e:
                swallow.noted("serve.reconcile", e)
            time.sleep(_RECONCILE_PERIOD_S)

    def shutdown(self) -> bool:
        with self._lock:
            self._shutdown = True
            for name in list(self._deployments):
                self._stop_replicas(name,
                                    len(self._replicas.get(name, [])))
            self._deployments.clear()
            self._cv.notify_all()
        return True
