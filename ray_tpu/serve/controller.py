"""ServeController: the serve control plane actor.

Parity: reference ``python/ray/serve/controller.py`` (:39
``ServeController``) + ``deployment_state.py`` (:45,602 reconciler) —
goal state per deployment (replica count, config), reconcile loop
creating/stopping replica actors, long-poll change notifications
(long_poll.py), queue-metric autoscaling (autoscaling_policy.py:
scale to ceil(total_queued / target_num_ongoing_requests_per_replica)
clamped to [min,max]).

Updates are *rolling* (reference ``deployment_state.py`` version-aware
reconciler): a redeploy that changes code/config marks live replicas as
old-version; the reconciler surges new-version replicas in, waits for
them to pass ``check_health``, then retires the same number of
old-version ones — serving capacity never drops below the target.  A
redeploy that changes only ``user_config`` skips restarts entirely and
calls ``reconfigure`` on the live replicas in place (reference
``deployment_state.py`` lightweight-update path).  The reconciler also
runs periodic health checks and replaces replicas that fail them
(reference ``replica.py`` health-check loop).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions

CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"

# Fraction of the target replica count a rolling update may surge above
# it while replacing old-version replicas (reference max_surge semantics).
_ROLLING_SURGE_FRACTION = 0.25
_HEALTH_CHECK_PERIOD_S = 2.5
_HEALTH_CHECK_FAILURE_THRESHOLD = 2
_RECONCILE_PERIOD_S = 0.25


class DeploymentInfo:
    def __init__(self, name: str, serialized_init, num_replicas: int,
                 ray_actor_options: Optional[dict] = None,
                 max_concurrent_queries: int = 100,
                 autoscaling_config: Optional[dict] = None,
                 route_prefix: Optional[str] = None,
                 version: int = 0):
        self.name = name
        self.serialized_init = serialized_init
        self.num_replicas = num_replicas
        self.ray_actor_options = dict(ray_actor_options or {})
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.route_prefix = route_prefix
        self.version = version

    def replica_fingerprint(self) -> tuple:
        """Everything that forces a replica restart when it changes —
        the deployment body and actor options, but NOT user_config
        (which reconfigures in place)."""
        deployment_def, init_args, init_kwargs, _user_config = \
            self.serialized_init
        return (deployment_def, init_args, init_kwargs,
                tuple(sorted(self.ray_actor_options.items())),
                self.max_concurrent_queries)


class _Replica:
    """A live replica actor and the deployment version it was built at."""

    __slots__ = ("handle", "version")

    def __init__(self, handle, version: int):
        self.handle = handle
        self.version = version


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._replicas: Dict[str, List[_Replica]] = {}
        self._config_version = 0
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        # Serializes whole reconcile passes (deploy handler vs loop):
        # replica startup blocks on health checks, so two concurrent
        # passes would both see the same deficit and double-start.
        self._reconcile_mutex = threading.Lock()
        self._shutdown = False
        self._last_health_check = 0.0
        self._health_fail_counts: Dict[_Replica, int] = {}
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # ---- API (called from serve.api) ----------------------------------
    def deploy(self, name: str, serialized_init, num_replicas: int = 1,
               ray_actor_options: Optional[dict] = None,
               max_concurrent_queries: int = 100,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = None) -> bool:
        with self._lock:
            if route_prefix is not None:
                for other, info in self._deployments.items():
                    if other != name and info.route_prefix == route_prefix:
                        raise ValueError(
                            f"route_prefix {route_prefix!r} is already "
                            f"used by deployment {other!r}")
            prev = self._deployments.get(name)
            version = (prev.version + 1) if prev else 0
            info = DeploymentInfo(
                name, serialized_init, num_replicas, ray_actor_options,
                max_concurrent_queries, autoscaling_config, route_prefix,
                version)
            self._deployments[name] = info
            lightweight = (
                prev is not None
                and prev.replica_fingerprint() == info.replica_fingerprint())
            replicas = list(self._replicas.get(name, ()))
            self._cv.notify_all()
        if lightweight:
            # Only user_config (or replica count / autoscaling / route)
            # changed: reconfigure live replicas in place, no restarts.
            # Under the reconcile mutex so two concurrent deploys can't
            # interleave their reconfigure waves out of order.  A
            # replica is version-bumped only AFTER its reconfigure
            # succeeds — on failure (rejected config, dead actor) it
            # stays old-version and the rolling reconciler replaces it
            # with a fresh replica built from the new serialized_init.
            user_config = serialized_init[3]
            with self._reconcile_mutex:
                # A later deploy may have won the mutex first: applying
                # this (older) wave would regress replicas to a stale
                # config, so skip it entirely.
                with self._lock:
                    cur = self._deployments.get(name)
                    stale = cur is None or cur.version != version
                if not stale:
                    # All reconfigures issued up front, gathered under
                    # one shared deadline — N hung replicas cost one
                    # timeout, not N, and we hold _reconcile_mutex here.
                    waves = [(rep,
                              rep.handle.reconfigure.remote(user_config)
                              if user_config is not None else None)
                             for rep in replicas]
                    deadline = time.monotonic() + 30.0
                    for rep, fut in waves:
                        try:
                            if fut is not None:
                                ray_tpu.get(fut, timeout=max(
                                    0.1, deadline - time.monotonic()))
                            rep.version = version
                        except Exception:
                            # Rejected config / hung or dead replica:
                            # stays old-version; the rolling reconciler
                            # replaces it with a fresh replica.
                            pass
            with self._lock:
                self._bump()
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            if name not in self._deployments:
                return False
            del self._deployments[name]
            self._stop_replicas(name, len(self._replicas.get(name, [])))
            self._replicas.pop(name, None)
            self._bump()
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            reps = self._replicas.get(name, [])
            return {"name": info.name, "num_replicas": info.num_replicas,
                    "version": info.version,
                    "num_running_replicas": len(reps),
                    "num_current_version_replicas":
                        sum(1 for r in reps if r.version == info.version)}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def get_deployment_spec(self, name: str):
        """(serialized_init, config dict) for rebuilding a Deployment
        (serve.get_deployment parity)."""
        with self._lock:
            info = self._deployments.get(name)
            if info is None:
                return None
            return (info.serialized_init, {
                "num_replicas": info.num_replicas,
                "ray_actor_options": info.ray_actor_options,
                "max_concurrent_queries": info.max_concurrent_queries,
                "autoscaling_config": info.autoscaling_config,
                "route_prefix": info.route_prefix,
            })

    def get_route_table(self) -> Dict[str, str]:
        """route_prefix -> deployment name (http_proxy route updates)."""
        with self._lock:
            return {info.route_prefix: name
                    for name, info in self._deployments.items()
                    if info.route_prefix}

    def get_replica_handles(self, name: str) -> List:
        # Old-version replicas keep serving until the rolling update
        # retires them, so the router sees all of them.
        with self._lock:
            return [r.handle for r in self._replicas.get(name, [])]

    # ---- long poll (reference long_poll.py) ---------------------------
    def listen_for_change(self, known_version: int, timeout: float = 10.0
                          ) -> int:
        """Blocks until the routing config version advances past
        ``known_version`` (or timeout); returns the current version."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._config_version <= known_version and \
                    not self._shutdown:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            return self._config_version

    def _bump(self):
        self._config_version += 1
        self._cv.notify_all()

    # ---- reconciliation ------------------------------------------------
    def _probe_inflight(self) -> Dict[str, Optional[int]]:
        """Queue-depth probes for autoscaled deployments, issued OUTSIDE
        ``self._lock`` — a slow replica must stall only the reconcile
        loop, never deploy/get_deployment_info/long-poll entry points.
        None = probe failed (caller keeps the current replica count)."""
        with self._lock:
            targets = {
                name: [r.handle for r in self._replicas.get(name, [])]
                for name, info in self._deployments.items()
                if info.autoscaling_config}
        # All probes issued up front against ONE shared deadline, so N
        # deployments with hung replicas cost one timeout, not N
        # (same shape as _maybe_health_check).
        futures = {name: [h.get_num_inflight.remote() for h in handles]
                   for name, handles in targets.items() if handles}
        deadline = time.monotonic() + 5.0
        out: Dict[str, Optional[int]] = {}
        for name, futs in futures.items():
            try:
                out[name] = sum(ray_tpu.get(
                    futs, timeout=max(0.1, deadline - time.monotonic())))
            except Exception:
                out[name] = None
        return out

    def _target_replicas(self, info: DeploymentInfo,
                         probed: Optional[int] = None) -> int:
        cfg = info.autoscaling_config
        if not cfg:
            return info.num_replicas
        n_current = len(self._replicas.get(info.name, []))
        if not n_current:
            return max(1, cfg.get("min_replicas", 1))
        if probed is None:
            return n_current      # probe failed: hold steady
        inflight = probed
        target_per = cfg.get("target_num_ongoing_requests_per_replica", 1)
        want = math.ceil(inflight / max(target_per, 1e-9)) if inflight \
            else cfg.get("min_replicas", 1)
        return max(cfg.get("min_replicas", 1),
                   min(cfg.get("max_replicas", 10), want))

    def _start_replicas(self, info: DeploymentInfo, count: int
                        ) -> List[_Replica]:
        from ray_tpu.serve.replica import ReplicaActor
        opts = dict(info.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        # +2 headroom so control calls (get_num_inflight, health) never
        # queue behind saturated request slots — the router, not actor
        # concurrency, enforces max_concurrent_queries.
        opts["max_concurrency"] = max(2, info.max_concurrent_queries) + 2
        cls = ray_tpu.remote(**opts)(ReplicaActor)
        return [_Replica(cls.remote(info.serialized_init), info.version)
                for _ in range(count)]

    def _adopt_or_kill(self, name: str, version: int,
                       new: List[_Replica]) -> bool:
        """Register freshly started replicas iff the deployment still
        wants that version; otherwise kill them.  Returns adopted?"""
        with self._lock:
            info = self._deployments.get(name)
            if info is not None and info.version == version:
                self._replicas.setdefault(name, []).extend(new)
                return True
        for rep in new:
            try:
                ray_tpu.kill(rep.handle)
            except Exception:
                pass
        return False

    def _wait_healthy(self, reps: List[_Replica], timeout: float = 30.0
                      ) -> List[_Replica]:
        """Block until each replica answers check_health (actor started
        and ctor ran); drop ones that error out.  All probes are issued
        up front so N hung replicas cost one timeout, not N."""
        futs = [(rep, rep.handle.check_health.remote()) for rep in reps]
        healthy = []
        deadline = time.monotonic() + timeout
        for rep, fut in futs:
            try:
                ray_tpu.get(fut, timeout=max(
                    0.1, deadline - time.monotonic()))
                healthy.append(rep)
            except Exception:
                try:
                    ray_tpu.kill(rep.handle)
                except Exception:
                    pass
        return healthy

    def _drain_and_kill(self, victims: List[_Replica],
                        drain_timeout: float = 10.0):
        """Retire replicas gracefully: they are already out of
        _replicas and the config version was bumped, so routers drop
        them on their next long-poll refresh; wait for in-flight
        requests (and the router refresh window) to drain before
        killing (reference replica graceful_shutdown loop)."""
        if not victims:
            return
        # Grace so routers' long-polls (woken by the bump) refetch the
        # replica set before we start judging in-flight counts.
        time.sleep(0.25)
        deadline = time.monotonic() + drain_timeout
        pending = list(victims)
        while pending and time.monotonic() < deadline:
            still = []
            for rep in pending:
                try:
                    if ray_tpu.get(rep.handle.get_num_inflight.remote(),
                                   timeout=2.0) > 0:
                        still.append(rep)
                except exceptions.GetTimeoutError:
                    # Slow to answer != dead: keep draining it until
                    # the overall deadline.
                    still.append(rep)
                except Exception:
                    pass   # dead already — nothing to drain
            pending = still
            if pending:
                time.sleep(0.05)
        for rep in victims:
            self._health_fail_counts.pop(rep, None)
            try:
                ray_tpu.kill(rep.handle)
            except Exception:
                pass

    def _reconcile_once(self):
        with self._reconcile_mutex:
            self._reconcile_locked()

    def _reconcile_locked(self):
        probes = self._probe_inflight()    # blocking gets, lock NOT held
        with self._lock:
            if self._shutdown:
                return
            scale_up: List[Tuple[str, DeploymentInfo, int]] = []
            rolling: List[Tuple[str, DeploymentInfo, int]] = []
            retire: List[_Replica] = []
            for name, info in self._deployments.items():
                reps = self._replicas.setdefault(name, [])
                want = self._target_replicas(info, probes.get(name))
                old = [r for r in reps if r.version != info.version]
                if len(reps) < want:
                    scale_up.append((name, info, want - len(reps)))
                elif len(reps) > want:
                    # Retire old-version replicas first when shrinking.
                    reps.sort(key=lambda r: r.version == info.version)
                    n_drop = len(reps) - want
                    retire.extend(reps[:n_drop])
                    self._replicas[name] = reps[n_drop:]
                    old = [r for r in self._replicas[name]
                           if r.version != info.version]
                if old:
                    surge = max(1, math.ceil(want * _ROLLING_SURGE_FRACTION))
                    rolling.append((name, info, min(surge, len(old))))
            if retire:
                self._bump()
        self._drain_and_kill(retire)
        for name, info, count in scale_up:
            new = self._wait_healthy(self._start_replicas(info, count))
            if new and self._adopt_or_kill(name, info.version, new):
                with self._lock:
                    self._bump()
        for name, info, count in rolling:
            # Surge `count` new-version replicas in, wait until they are
            # serving, then retire `count` old-version ones.
            new = self._wait_healthy(self._start_replicas(info, count))
            if not new:
                continue
            if not self._adopt_or_kill(name, info.version, new):
                continue
            with self._lock:
                reps = self._replicas.get(name, [])
                old = [r for r in reps if r.version != info.version]
                victims = old[:len(new)]
                self._replicas[name] = [r for r in reps
                                        if r not in victims]
                self._bump()
            self._drain_and_kill(victims)
        self._maybe_health_check()

    def _maybe_health_check(self):
        now = time.monotonic()
        if now - self._last_health_check < _HEALTH_CHECK_PERIOD_S:
            return
        self._last_health_check = now
        with self._lock:
            snapshot = {name: list(reps)
                        for name, reps in self._replicas.items()}
        # All probes issued up front against ONE shared deadline, so N
        # hung replicas cost one timeout — and this runs under the
        # reconcile mutex, where a long stall would block deploys.
        probes = [(name, rep, rep.handle.check_health.remote())
                  for name, reps in snapshot.items() for rep in reps]
        deadline = time.monotonic() + 10.0
        dead: List[Tuple[str, _Replica]] = []
        for name, rep, fut in probes:
            try:
                ray_tpu.get(fut, timeout=max(
                    0.1, deadline - time.monotonic()))
                self._health_fail_counts.pop(rep, None)
            except exceptions.GetTimeoutError:
                # Slow answers only count toward a consecutive-failure
                # threshold (reference health loop): one long GC pause
                # or load spike is not death.
                fails = self._health_fail_counts.get(rep, 0) + 1
                self._health_fail_counts[rep] = fails
                if fails >= _HEALTH_CHECK_FAILURE_THRESHOLD:
                    dead.append((name, rep))
            except Exception:
                # The probe itself failed (actor died, user
                # check_health raised): definitively unhealthy.
                dead.append((name, rep))
        if not dead:
            return
        with self._lock:
            for name, rep in dead:
                reps = self._replicas.get(name)
                if reps and rep in reps:
                    reps.remove(rep)
                self._health_fail_counts.pop(rep, None)
            self._bump()
        # Drain whatever is still answering before the kill; a truly
        # dead replica drains instantly (its probe raises non-timeout).
        self._drain_and_kill([rep for _, rep in dead], drain_timeout=5.0)
        # The next reconcile pass scales the deployment back up.

    def _stop_replicas(self, name: str, count: int):
        # Must hold lock.
        reps = self._replicas.get(name, [])
        victims, self._replicas[name] = reps[:count], reps[count:]
        for rep in victims:
            self._health_fail_counts.pop(rep, None)
            try:
                ray_tpu.kill(rep.handle)
            except Exception:
                pass

    def _reconcile_loop(self):
        from ray_tpu._private.debug import swallow
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception as e:
                swallow.noted("serve.reconcile", e)
            time.sleep(_RECONCILE_PERIOD_S)

    def shutdown(self) -> bool:
        with self._lock:
            self._shutdown = True
            for name in list(self._deployments):
                self._stop_replicas(name,
                                    len(self._replicas.get(name, [])))
            self._deployments.clear()
            self._cv.notify_all()
        return True
