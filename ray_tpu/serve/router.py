"""Router: picks a replica for each request.

Parity: reference ``python/ray/serve/router.py:170`` —
``Router.assign_request``: round-robin over the replica set with
backpressure (skip replicas at ``max_concurrent_queries``; block when
all are saturated), replica set refreshed via the controller long-poll
(``long_poll.py`` ``LongPollClient``).

Load signals: the router counts callers parked in ``assign_request``
(the true request queue — replicas only ever see ``max_concurrent``
of them) and ships that depth to the controller on a small reporter
thread; together with the replicas' in-flight counts it is the
autoscaler's queue-depth signal.

Failure handling: :meth:`call` (the blocking path used by the HTTP
proxy and ``DeploymentHandle.call``) re-assigns a request whose replica
died mid-flight — the dead replica is evicted from the local set
immediately (no waiting for the controller's health check), the
request retries on a survivor up to ``serve_request_retries`` times,
and the client sees exactly one response or an error that names the
deployment, the attempts, and the underlying death.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions
from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.debug import swallow
from ray_tpu._private.debug.lock_order import diag_condition


class ReplicaDiedError(exceptions.RayTpuError):
    """A serve request ran out of replica-death retries; carries the
    attribution the client needs (deployment, attempts, last error)."""

    def __init__(self, deployment: str, attempts: int,
                 cause: BaseException):
        self.deployment = deployment
        self.attempts = attempts
        self.cause = cause
        super().__init__(
            f"deployment {deployment!r}: replica died mid-request "
            f"({attempts} attempt(s)); last error: "
            f"{type(cause).__name__}: {cause}")


#: Failures that mean "the replica is gone", not "the request is bad" —
#: the only ones the router may transparently re-assign.
_DEATH_ERRORS = (exceptions.ActorError, exceptions.WorkerCrashedError,
                 exceptions.NodeDiedError, exceptions.OwnerDiedError)


def controller_alive() -> bool:
    """Whether the serve controller actor is still registered."""
    from ray_tpu.serve.controller import CONTROLLER_NAME
    try:
        ray_tpu.get_actor(CONTROLLER_NAME)
        return True
    except Exception:
        return False


class Router:
    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100):
        self._controller = controller
        self._name = deployment_name
        self._router_id = uuid.uuid4().hex[:12]
        self._max_q = max_concurrent_queries
        self._replicas: List = []
        self._inflight: Dict[int, int] = {}  # replica idx -> inflight
        self._rr = itertools.count()
        self._lock = diag_condition(name="serve.Router._lock")
        self._version = -1
        self._queued = 0          # callers parked in assign_request
        self._stopped = threading.Event()
        self.stats = {"requests": 0, "death_retries": 0,
                      "dropped_dispatches": 0, "evicted_replicas": 0}
        self._refresh(block=True)
        self._poll_thread = threading.Thread(
            target=self._long_poll_loop, daemon=True,
            name=f"serve-router-{deployment_name}")
        self._poll_thread.start()
        self._report_thread = threading.Thread(
            target=self._report_loop, daemon=True,
            name=f"serve-router-report-{deployment_name}")
        self._report_thread.start()

    # ---- replica set maintenance ---------------------------------------
    def _refresh(self, block: bool = False):
        deadline = time.monotonic() + 10.0
        while True:
            handles = ray_tpu.get(
                self._controller.get_replica_handles.remote(self._name))
            if handles or not block:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment {self._name!r}")
            time.sleep(0.05)
        with self._lock:
            self._replicas = handles
            self._inflight = {i: 0 for i in range(len(handles))}
            self._lock.notify_all()

    def _evict_replica(self, replica) -> None:
        """Drop a dead replica from the local set NOW — re-assignment
        must not wait for the controller's next health-check pass."""
        with self._lock:
            if replica not in self._replicas:
                return
            self._replicas = [r for r in self._replicas if r is not replica]
            self._inflight = {i: 0 for i in range(len(self._replicas))}
            self.stats["evicted_replicas"] += 1
            self._lock.notify_all()

    def stop(self):
        """Stop the long-poll + reporter threads (router unusable)."""
        self._stopped.set()

    def _long_poll_loop(self):
        # A transient listen_for_change failure must not pin a stale
        # replica set forever: retry with backoff and exit only when the
        # router is stopped or the controller is confirmed gone.
        backoff = 0.05
        while not self._stopped.is_set():
            try:
                version = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, 5.0))
                backoff = 0.05
                if self._stopped.is_set():
                    return
                if version != self._version:
                    self._version = version
                    self._refresh()
            except Exception as e:
                if self._stopped.is_set() or not controller_alive():
                    return
                swallow.noted("serve.router.long_poll", e)
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, 2.0)

    def _report_loop(self):
        """Ship this router's parked-caller depth to the controller —
        the autoscaler's queue-depth sample.  Idle routers go silent
        after one zero report (no steady-state chatter)."""
        interval = get_config().serve_router_report_interval_s
        last = -1
        while not self._stopped.is_set():
            with self._lock:
                queued = self._queued
            if queued != 0 or last != 0:
                try:
                    self._controller.report_router_queue.remote(
                        self._name, self._router_id, queued)
                except Exception as e:
                    if self._stopped.is_set() or not controller_alive():
                        return
                    swallow.noted("serve.router.report", e)
                try:
                    from ray_tpu._private.metrics_agent import (
                        get_metrics_registry)
                    reg = get_metrics_registry()
                    reg.register("ray_tpu_serve_router_queued", "gauge")
                    reg.set("ray_tpu_serve_router_queued", float(queued),
                            (("deployment", self._name),))
                except Exception as e:
                    swallow.noted("serve.router.report_metrics", e)
            last = queued
            self._stopped.wait(interval)

    # ---- request path ---------------------------------------------------
    def _assign(self, method_name: str, args, kwargs) -> Tuple:
        """Pick a replica (round-robin + backpressure) and submit.
        Returns ``(ref, replica_handle)``."""
        deadline = time.monotonic() + 30.0
        with self._lock:
            self._queued += 1
        try:
            while True:
                # serve.request failure point: per-deployment error /
                # delay / drop ("drop" = this dispatch is lost in
                # flight — the router re-assigns, modeling a replica
                # that vanished between pick and submit).
                dropped = fault_injection.hook(
                    "serve.request", deployment=self._name) == "drop"
                with self._lock:
                    n = len(self._replicas)
                    if n:
                        for _ in range(n):
                            i = next(self._rr) % n
                            if self._inflight.get(i, 0) < self._max_q:
                                self._inflight[i] = \
                                    self._inflight.get(i, 0) + 1
                                replica = self._replicas[i]
                                break
                        else:
                            replica = None
                    else:
                        replica = None
                    if replica is None:
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"deployment {self._name!r}: all replicas "
                                "saturated for 30s")
                        self._lock.wait(timeout=0.1)
                        continue
                if dropped:
                    # The dispatch is "lost": release the slot and pick
                    # again (counts as a re-assignment, not an error).
                    self.stats["dropped_dispatches"] += 1
                    with self._lock:
                        if i in self._inflight:
                            self._inflight[i] -= 1
                        self._lock.notify_all()
                    continue
                ref = replica.handle_request.remote(
                    method_name, args, kwargs)
                self._track(ref, i)
                self.stats["requests"] += 1
                return ref, replica
        finally:
            with self._lock:
                self._queued -= 1

    def assign_request(self, method_name: str, args, kwargs):
        """Round-robin with backpressure; returns an ObjectRef."""
        ref, _replica = self._assign(method_name, args, kwargs)
        return ref

    def call(self, method_name: str, args, kwargs,
             timeout: float = 60.0):
        """Blocking request with replica-death re-assignment: the path
        the HTTP proxy rides.  Retries ONLY on replica death (never on
        user exceptions), evicting the dead replica locally so the
        retry lands on a survivor; after ``serve_request_retries``
        deaths the client gets a :class:`ReplicaDiedError` naming the
        deployment and attempts."""
        retries = get_config().serve_request_retries
        attempt = 0
        while True:
            attempt += 1
            ref, replica = self._assign(method_name, args, kwargs)
            try:
                return ray_tpu.get(ref, timeout=timeout)
            except _DEATH_ERRORS as e:
                self._evict_replica(replica)
                if attempt > retries:
                    raise ReplicaDiedError(self._name, attempt, e) from e
                self.stats["death_retries"] += 1

    def _track(self, ref, idx: int):
        def done(_fut):
            with self._lock:
                if idx in self._inflight:
                    self._inflight[idx] -= 1
                self._lock.notify_all()
        ref.future().add_done_callback(done)
