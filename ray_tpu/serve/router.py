"""Router: picks a replica for each request.

Parity: reference ``python/ray/serve/router.py:170`` —
``Router.assign_request``: round-robin over the replica set with
backpressure (skip replicas at ``max_concurrent_queries``; block when
all are saturated), replica set refreshed via the controller long-poll
(``long_poll.py`` ``LongPollClient``).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

import ray_tpu


def controller_alive() -> bool:
    """Whether the serve controller actor is still registered."""
    from ray_tpu.serve.controller import CONTROLLER_NAME
    try:
        ray_tpu.get_actor(CONTROLLER_NAME)
        return True
    except Exception:
        return False


class Router:
    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100):
        self._controller = controller
        self._name = deployment_name
        self._max_q = max_concurrent_queries
        self._replicas: List = []
        self._inflight: Dict[int, int] = {}  # replica idx -> inflight
        self._rr = itertools.count()
        self._lock = threading.Condition()
        self._version = -1
        self._stopped = threading.Event()
        self._refresh(block=True)
        self._poll_thread = threading.Thread(
            target=self._long_poll_loop, daemon=True,
            name=f"serve-router-{deployment_name}")
        self._poll_thread.start()

    # ---- replica set maintenance ---------------------------------------
    def _refresh(self, block: bool = False):
        deadline = time.monotonic() + 10.0
        while True:
            handles = ray_tpu.get(
                self._controller.get_replica_handles.remote(self._name))
            if handles or not block:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment {self._name!r}")
            time.sleep(0.05)
        with self._lock:
            self._replicas = handles
            self._inflight = {i: 0 for i in range(len(handles))}
            self._lock.notify_all()

    def stop(self):
        """Stop the long-poll thread (router no longer usable)."""
        self._stopped.set()

    def _long_poll_loop(self):
        # A transient listen_for_change failure must not pin a stale
        # replica set forever: retry with backoff and exit only when the
        # router is stopped or the controller is confirmed gone.
        backoff = 0.05
        while not self._stopped.is_set():
            try:
                version = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, 5.0))
                backoff = 0.05
                if self._stopped.is_set():
                    return
                if version != self._version:
                    self._version = version
                    self._refresh()
            except Exception:
                if self._stopped.is_set() or not controller_alive():
                    return
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, 2.0)

    # ---- request path ---------------------------------------------------
    def assign_request(self, method_name: str, args, kwargs):
        """Round-robin with backpressure; returns an ObjectRef."""
        deadline = time.monotonic() + 30.0
        while True:
            with self._lock:
                n = len(self._replicas)
                if n:
                    for _ in range(n):
                        i = next(self._rr) % n
                        if self._inflight.get(i, 0) < self._max_q:
                            self._inflight[i] = \
                                self._inflight.get(i, 0) + 1
                            replica = self._replicas[i]
                            break
                    else:
                        replica = None
                else:
                    replica = None
                if replica is None:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"deployment {self._name!r}: all replicas "
                            "saturated for 30s")
                    self._lock.wait(timeout=0.1)
                    continue
            ref = replica.handle_request.remote(method_name, args, kwargs)
            self._track(ref, i)
            return ref

    def _track(self, ref, idx: int):
        def done(_fut):
            with self._lock:
                if idx in self._inflight:
                    self._inflight[idx] -= 1
                self._lock.notify_all()
        ref.future().add_done_callback(done)
