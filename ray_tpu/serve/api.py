"""Public serve API: ``@serve.deployment``, start/run/delete/shutdown.

Parity: reference ``python/ray/serve/api.py`` — ``@serve.deployment``
(:1032), ``serve.start`` (:468), ``serve.run`` (:1437),
``get_deployment``/``list_deployments`` (:1569,:1608).  The controller is
a named detached actor; deployment handles route through an in-process
``Router`` kept fresh by the controller's long-poll.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.router import Router

_PROXY_NAME = "SERVE_PROXY_ACTOR"

_controller = None
_proxy = None
# One live Router per deployment per process: handles share it, so
# repeated get_handle() calls don't each spawn a long-poll thread.
_handle_routers: Dict[str, Router] = {}


def start(detached: bool = True, http_options: Optional[dict] = None):
    """Start (or connect to) the serve instance: the controller actor
    plus, unless ``http_options`` is ``{"location": "NoServer"}``, an
    HTTP proxy actor (reference ``serve.start``, ``http_proxy.py``)."""
    global _controller, _proxy
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            _controller = ray_tpu.remote(
                num_cpus=0, name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=32)(ServeController).remote()
            ray_tpu.get(_controller.list_deployments.remote())
    http_options = dict(http_options or {})
    if http_options.get("location") != "NoServer" and _proxy is None:
        from ray_tpu.serve.http_proxy import HTTPProxyActor
        host = http_options.get("host", "127.0.0.1")
        port = http_options.get("port", 8000)
        try:
            _proxy = ray_tpu.get_actor(_PROXY_NAME)
        except Exception:
            _proxy = ray_tpu.remote(
                num_cpus=0, name=_PROXY_NAME, lifetime="detached",
                max_concurrency=4)(HTTPProxyActor).remote(host, port)
        actual_port = ray_tpu.get(_proxy.ready.remote())
        if port and actual_port != port:
            import warnings
            warnings.warn(
                f"serve HTTP proxy already running on port {actual_port}; "
                f"requested port {port} ignored", RuntimeWarning)
    return _controller


def _get_controller():
    start(http_options={"location": "NoServer"})
    return _controller


class Deployment:
    """A configured (but not necessarily deployed) serve deployment.

    Reference ``python/ray/serve/api.py:786`` (class Deployment)."""

    def __init__(self, func_or_class, name: str,
                 num_replicas: int = 1,
                 init_args: Optional[tuple] = None,
                 init_kwargs: Optional[dict] = None,
                 route_prefix: Optional[str] = "__default__",
                 ray_actor_options: Optional[dict] = None,
                 user_config: Any = None,
                 max_concurrent_queries: int = 100,
                 autoscaling_config: Optional[dict] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.init_args = tuple(init_args or ())
        self.init_kwargs = dict(init_kwargs or {})
        if route_prefix == "__default__":
            route_prefix = f"/{name}"
        if route_prefix is not None and not route_prefix.startswith("/"):
            raise ValueError("route_prefix must start with '/'")
        self.route_prefix = route_prefix
        self.ray_actor_options = dict(ray_actor_options or {})
        self.user_config = user_config
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config

    # -- lifecycle ------------------------------------------------------
    def deploy(self, *init_args, **init_kwargs) -> None:
        """Deploy (or redeploy) this deployment (reference
        ``Deployment.deploy``, api.py:888)."""
        controller = _get_controller()
        args = init_args or self.init_args
        kwargs = init_kwargs or self.init_kwargs
        serialized_init = (self._func_or_class, args, kwargs,
                          self.user_config)
        ray_tpu.get(controller.deploy.remote(
            self.name, serialized_init, self.num_replicas,
            self.ray_actor_options, self.max_concurrent_queries,
            self.autoscaling_config, self.route_prefix))
        # Block until at least one replica is running (reference deploy
        # blocks on goal completion).
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            info = ray_tpu.get(
                controller.get_deployment_info.remote(self.name))
            if info and info["num_running_replicas"] > 0:
                return
            time.sleep(0.02)
        raise TimeoutError(f"deployment {self.name!r} failed to start")

    def delete(self) -> None:
        controller = _get_controller()
        ray_tpu.get(controller.delete_deployment.remote(self.name))
        _evict_router(self.name)

    def get_handle(self) -> DeploymentHandle:
        controller = _get_controller()
        router = _handle_routers.get(self.name)
        if router is None or router._stopped.is_set():
            router = Router(
                controller, self.name,
                max_concurrent_queries=self.max_concurrent_queries)
            _handle_routers[self.name] = router
        return DeploymentHandle(self.name, router)

    # -- configuration --------------------------------------------------
    def options(self, **kwargs) -> "Deployment":
        """Return a copy with config overrides (api.py:941)."""
        cfg = dict(
            func_or_class=self._func_or_class, name=self.name,
            num_replicas=self.num_replicas, init_args=self.init_args,
            init_kwargs=self.init_kwargs, route_prefix=self.route_prefix,
            ray_actor_options=self.ray_actor_options,
            user_config=self.user_config,
            max_concurrent_queries=self.max_concurrent_queries,
            autoscaling_config=self.autoscaling_config)
        cfg.update(kwargs)
        return Deployment(**cfg)

    def bind(self, *args, **kwargs):
        """Author a deployment-DAG node (reference serve pipeline
        ``.bind``): class deployments yield a ClassNode whose methods
        are further bindable; function deployments yield a call node."""
        import inspect

        from ray_tpu.serve import pipeline
        if inspect.isclass(self._func_or_class):
            return pipeline.ClassNode(self, args, kwargs)
        return pipeline.FunctionNode(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "Deployments cannot be called directly; use "
            "`deployment.deploy()` then `deployment.get_handle()` or HTTP.")

    def __repr__(self):
        return f"Deployment(name={self.name!r})"


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None,
               num_replicas: int = 1,
               init_args: Optional[tuple] = None,
               init_kwargs: Optional[dict] = None,
               route_prefix: Optional[str] = "__default__",
               ray_actor_options: Optional[dict] = None,
               user_config: Any = None,
               max_concurrent_queries: int = 100,
               autoscaling_config: Optional[dict] = None):
    """``@serve.deployment`` decorator (reference api.py:1032)."""

    def wrap(func_or_class):
        return Deployment(
            func_or_class, name or func_or_class.__name__,
            num_replicas=num_replicas, init_args=init_args,
            init_kwargs=init_kwargs, route_prefix=route_prefix,
            ray_actor_options=ray_actor_options, user_config=user_config,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling_config=autoscaling_config)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(target, host: str = "127.0.0.1", port: int = 8000,
        route_prefix: str = "/"):
    """Deploy ``target`` with an HTTP ingress and return its handle
    (reference ``serve.run``, api.py:1437).  ``target`` may be a
    Deployment or a pipeline DAG node (``Deployment.bind(...)``) — the
    latter builds the whole graph behind the route."""
    from ray_tpu.serve.pipeline import DAGNode, build
    start(http_options={"host": host, "port": port})
    if isinstance(target, DAGNode):
        return build(target, http_route=route_prefix)
    if route_prefix != target.route_prefix:
        target = target.options(route_prefix=route_prefix)
    target.deploy()
    return target.get_handle()


def get_deployment(name: str) -> Deployment:
    """Fetch a live deployment by name (reference api.py:1569)."""
    controller = _get_controller()
    spec = ray_tpu.get(controller.get_deployment_spec.remote(name))
    if spec is None:
        raise KeyError(f"no deployment {name!r}")
    serialized_init, cfg = spec
    func_or_class, init_args, init_kwargs, user_config = serialized_init
    return Deployment(
        func_or_class, name, num_replicas=cfg["num_replicas"],
        init_args=init_args, init_kwargs=init_kwargs,
        route_prefix=cfg["route_prefix"],
        ray_actor_options=cfg["ray_actor_options"],
        user_config=user_config,
        max_concurrent_queries=cfg["max_concurrent_queries"],
        autoscaling_config=cfg["autoscaling_config"])


def list_deployments() -> Dict[str, Deployment]:
    """All live deployments by name (reference api.py:1608)."""
    controller = _get_controller()
    return {name: get_deployment(name)
            for name in ray_tpu.get(controller.list_deployments.remote())}


def _evict_router(name: str) -> None:
    """Stop and drop the cached per-process Router for a deployment so a
    later ``get_handle()`` never reuses a stale replica set or the old
    ``max_concurrent_queries``."""
    router = _handle_routers.pop(name, None)
    if router is not None:
        router.stop()


def delete(name: str) -> None:
    controller = _get_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))
    _evict_router(name)


def shutdown() -> None:
    """Tear down all deployments, the proxy, and the controller."""
    global _controller, _proxy
    controller, proxy = _controller, _proxy
    _controller = _proxy = None
    for router in _handle_routers.values():
        router.stop()
    _handle_routers.clear()
    if controller is None:
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            controller = None
    if proxy is None:
        try:
            proxy = ray_tpu.get_actor(_PROXY_NAME)
        except Exception:
            proxy = None
    from ray_tpu._private.debug import swallow
    if proxy is not None:
        try:
            ray_tpu.get(proxy.stop.remote())
            ray_tpu.kill(proxy)
        except Exception as e:
            swallow.noted("serve.api.shutdown_proxy", e)
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote())
            ray_tpu.kill(controller)
        except Exception as e:
            swallow.noted("serve.api.shutdown_controller", e)
