"""DeploymentHandle: Python-side entry to a deployment.

Parity: reference ``python/ray/serve/handle.py`` — ``RayServeHandle``:
``handle.remote(*args)`` routes through the Router and returns an
ObjectRef; ``handle.method_name.remote(...)`` targets a method
(``.options(method_name=...)`` in the reference).
"""

from __future__ import annotations

from typing import Any


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs):
        return self._handle._router.assign_request(self._method, args,
                                                   kwargs)


def _rebuild_handle(deployment_name: str) -> "DeploymentHandle":
    from ray_tpu import serve
    return serve.get_deployment(deployment_name).get_handle()


class DeploymentHandle:
    def __init__(self, deployment_name: str, router):
        self.deployment_name = deployment_name
        self._router = router

    def __reduce__(self):
        # Handles travel inside task args / deployment init args
        # (pipeline composition); the router is process-local state, so
        # reconstruct from the name on the receiving side (reference
        # RayServeHandle serialization).
        return (_rebuild_handle, (self.deployment_name,))

    def remote(self, *args, **kwargs):
        return self._router.assign_request("__call__", args, kwargs)

    def options(self, method_name: str = "__call__") -> _MethodCaller:
        return _MethodCaller(self, method_name)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
