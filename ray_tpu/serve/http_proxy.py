"""HTTP ingress for serve deployments.

Parity: reference ``python/ray/serve/http_proxy.py`` —
``HTTPProxyActor`` (:180) runs an HTTP server per node whose route table
is pushed from the controller via long-poll (:308 route updates); each
request is routed to a replica through a ``Router``.  The reference uses
uvicorn/starlette; here the server is a stdlib ``ThreadingHTTPServer``
living inside the proxy actor, and the request object handed to user
code is a plain :class:`HTTPRequest` (picklable, starlette-free).
"""

from __future__ import annotations

import json
import threading
import traceback
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qsl, urlsplit

import ray_tpu
from ray_tpu._private.debug import swallow
from ray_tpu._private.debug.lock_order import diag_lock


@dataclass
class HTTPRequest:
    """What a deployment's ``__call__`` receives for an HTTP request."""
    method: str
    path: str                      # path *below* the route prefix
    route_prefix: str
    query_params: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body) if self.body else None


class HTTPProxyActor:
    """Serves HTTP on (host, port); routes by longest matching prefix."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.serve.controller import CONTROLLER_NAME
        self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes: Dict[str, str] = {}      # prefix -> deployment name
        self._routers: Dict[str, "Router"] = {}
        self._routes_lock = diag_lock("serve.HTTPProxyActor._routes_lock")
        self._version = -1
        self._refresh_routes()
        self._stopped = threading.Event()

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):           # silence stderr spam
                pass

            def _dispatch(self):
                try:
                    status, payload, ctype = proxy._handle(self)
                except Exception:
                    status, payload, ctype = (
                        500, traceback.format_exc().encode(), "text/plain")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_DELETE = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._serve_thread.start()
        self._poll_thread = threading.Thread(
            target=self._long_poll_loop, daemon=True,
            name="serve-proxy-longpoll")
        self._poll_thread.start()

    # -- control --------------------------------------------------------
    def ready(self) -> int:
        return self._port

    def stop(self) -> bool:
        self._stopped.set()
        self._server.shutdown()
        with self._routes_lock:
            routers, self._routers = list(self._routers.values()), {}
        for router in routers:
            router.stop()
        return True

    # -- route table maintenance ---------------------------------------
    def _refresh_routes(self):
        table = ray_tpu.get(self._controller.get_route_table.remote())
        with self._routes_lock:
            self._routes = table
            # Drop (and stop) routers for deployments that disappeared.
            for name in list(self._routers):
                if name not in table.values():
                    router = self._routers.pop(name, None)
                    if router is not None:
                        router.stop()

    def _long_poll_loop(self):
        # Retry transient failures with backoff — a single hiccup must not
        # freeze the route table; exit only on stop or controller death.
        from ray_tpu.serve.router import controller_alive
        backoff = 0.05
        while not self._stopped.is_set():
            try:
                version = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._version, 5.0))
                backoff = 0.05
                if version != self._version:
                    self._version = version
                    self._refresh_routes()
            except Exception as e:
                if self._stopped.is_set() or not controller_alive():
                    return
                swallow.noted("serve.http_proxy.long_poll", e)
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, 2.0)

    def _router_for(self, name: str):
        from ray_tpu.serve.router import Router
        with self._routes_lock:
            router = self._routers.get(name)
        if router is None:
            # Honor the deployment's own backpressure limit — the router
            # is what enforces max_concurrent_queries.
            spec = ray_tpu.get(
                self._controller.get_deployment_spec.remote(name))
            mcq = spec[1]["max_concurrent_queries"] if spec else 100
            router = Router(self._controller, name,
                            max_concurrent_queries=mcq)
            with self._routes_lock:
                existing = self._routers.setdefault(name, router)
            if existing is not router:
                router.stop()
                router = existing
        return router

    # -- request path ---------------------------------------------------
    def _match(self, path: str) -> Optional[Tuple[str, str]]:
        with self._routes_lock:
            routes = dict(self._routes)
        best = None
        for prefix, name in routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best

    def _handle(self, handler) -> Tuple[int, bytes, str]:
        split = urlsplit(handler.path)
        match = self._match(split.path)
        if match is None:
            return 404, b"no deployment for path", "text/plain"
        prefix, name = match
        length = int(handler.headers.get("Content-Length") or 0)
        body = handler.rfile.read(length) if length else b""
        request = HTTPRequest(
            method=handler.command,
            path=split.path[len(prefix.rstrip("/")):] or "/",
            route_prefix=prefix,
            query_params=dict(parse_qsl(split.query)),
            headers={k.lower(): v for k, v in handler.headers.items()},
            body=body)
        router = self._router_for(name)
        # Router.call re-assigns on replica death (bounded by
        # serve_request_retries): an HTTP client whose replica is
        # SIGKILLed mid-request gets a survivor's response, or a 500
        # naming the deployment — never a silent hang.
        result = router.call("__call__", (request,), {})
        if isinstance(result, bytes):
            return 200, result, "application/octet-stream"
        if isinstance(result, str):
            return 200, result.encode(), "text/plain"
        return 200, json.dumps(result).encode(), "application/json"
