"""Replica actor: wraps the user's deployment callable.

Parity: reference ``python/ray/serve/replica.py`` — ``RayServeReplica``
wraps the user class/function, counts in-flight requests (the router's
backpressure signal), runs reconfigure, reports health.
"""

from __future__ import annotations

import threading

import ray_tpu
import time
from typing import Any, Callable, Dict, Optional


class ReplicaActor:
    def __init__(self, serialized_init):
        deployment_def, init_args, init_kwargs, user_config = serialized_init
        if isinstance(deployment_def, type):
            self._callable = deployment_def(*init_args, **(init_kwargs or {}))
        else:
            self._callable = deployment_def
        self._is_function = not isinstance(deployment_def, type)
        self._inflight = 0
        self._lock = threading.Lock()
        self.num_requests = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            self._inflight += 1
            self.num_requests += 1
        try:
            if self._is_function:
                target = self._callable
            elif method_name in ("__call__", "", None):
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            # ObjectRef args resolve before the user callable sees them
            # (reference serve handle semantics; the pipeline DAG wires
            # upstream deployment outputs through as refs).
            from ray_tpu._private.object_ref import ObjectRef
            args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                    for a in args]
            kwargs = {k: ray_tpu.get(v) if isinstance(v, ObjectRef)
                      else v for k, v in (kwargs or {}).items()}
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._inflight -= 1

    def get_num_inflight(self) -> int:
        return self._inflight

    def get_metrics(self) -> Dict[str, float]:
        return {"num_requests": self.num_requests,
                "inflight": self._inflight}

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True
