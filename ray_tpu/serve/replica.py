"""Replica actor: wraps the user's deployment callable.

Parity: reference ``python/ray/serve/replica.py`` — ``RayServeReplica``
wraps the user class/function, counts in-flight requests (the router's
backpressure signal), runs reconfigure, reports health.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu._private.debug.lock_order import diag_lock


class ReplicaActor:
    def __init__(self, serialized_init, deployment_name: str = ""):
        deployment_def, init_args, init_kwargs, user_config = serialized_init
        # ObjectRef init args materialize HERE, in the replica (cold
        # start): model weights deploy as `Model.deploy(weights_ref)`
        # and each replica pulls the object through the data plane —
        # N replicas starting concurrently on different nodes form a
        # relay chain (transfer.relay), so the origin serves ~one copy
        # instead of N head pulls.
        from ray_tpu._private.object_ref import ObjectRef
        init_args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef)
                          else a for a in (init_args or ()))
        init_kwargs = {k: ray_tpu.get(v) if isinstance(v, ObjectRef)
                       else v for k, v in (init_kwargs or {}).items()}
        if isinstance(deployment_def, type):
            self._callable = deployment_def(*init_args, **(init_kwargs or {}))
        else:
            self._callable = deployment_def
        self._is_function = not isinstance(deployment_def, type)
        self._deployment = deployment_name
        self._inflight = 0
        self._lock = diag_lock("serve.ReplicaActor._lock")
        self.num_requests = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            self._inflight += 1
            self.num_requests += 1
        started = time.monotonic()
        try:
            if self._is_function:
                target = self._callable
            elif method_name in ("__call__", "", None):
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            # ObjectRef args resolve before the user callable sees them
            # (reference serve handle semantics; the pipeline DAG wires
            # upstream deployment outputs through as refs — the
            # zero-copy object-id handoff: the payload materializes
            # HERE, straight off the data plane, never in the router).
            from ray_tpu._private.object_ref import ObjectRef
            args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                    for a in args]
            kwargs = {k: ray_tpu.get(v) if isinstance(v, ObjectRef)
                      else v for k, v in (kwargs or {}).items()}
            # Label @serve.batch flush metrics with this deployment for
            # the duration of the user call (thread-local).
            from ray_tpu.serve import batching
            batching.set_batch_context(self._deployment or None)
            try:
                return target(*args, **kwargs)
            finally:
                batching.set_batch_context(None)
        finally:
            with self._lock:
                self._inflight -= 1
            try:
                from ray_tpu._private.metrics_agent import observe_internal
                observe_internal(
                    "ray_tpu_serve_request_seconds",
                    time.monotonic() - started,
                    deployment=self._deployment or "?",
                    method=method_name or "__call__")
            except Exception as e:
                from ray_tpu._private.debug import swallow
                swallow.noted("serve.replica.metrics", e)

    def get_num_inflight(self) -> int:
        return self._inflight

    def get_metrics(self) -> Dict[str, float]:
        return {"num_requests": self.num_requests,
                "inflight": self._inflight}

    def prepare_shutdown(self) -> bool:
        """Best-effort teardown ahead of the controller's kill: fail any
        requests still parked in @serve.batch queues instead of leaving
        their callers to time out."""
        from ray_tpu.serve import batching
        try:
            if not self._is_function:
                batching.close_instance_queues(self._callable)
        except Exception as e:
            from ray_tpu._private.debug import swallow
            swallow.noted("serve.replica.prepare_shutdown", e)
        return True

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True
