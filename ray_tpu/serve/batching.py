"""@serve.batch: transparent request batching inside a replica.

Parity: reference ``python/ray/serve/batching.py`` — concurrent calls
to the decorated method are queued; a flusher invokes the underlying
function ONCE with the list of requests when ``max_batch_size`` is
reached or ``batch_wait_timeout_s`` elapses; each caller gets its own
element of the returned list. Callers are concurrent actor-thread
requests here (the reference's are asyncio tasks).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional


class _Pending:
    __slots__ = ("arg", "event", "result", "error")

    def __init__(self, arg):
        self.arg = arg
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._flush_scheduled = False

    def submit(self, self_obj, arg) -> Any:
        p = _Pending(arg)
        flush_now = False
        with self._lock:
            self._queue.append(p)
            if len(self._queue) >= self._max:
                flush_now = True
            elif not self._flush_scheduled:
                self._flush_scheduled = True
                t = threading.Timer(self._timeout, self._flush, (self_obj,))
                t.daemon = True
                t.start()
        if flush_now:
            self._flush(self_obj)
        p.event.wait(timeout=60.0)
        if p.error is not None:
            raise p.error
        return p.result

    def _flush(self, self_obj):
        with self._lock:
            batch, self._queue = self._queue, []
            self._flush_scheduled = False
        if not batch:
            return
        try:
            args = [p.arg for p in batch]
            results = self._fn(self_obj, args) if self_obj is not None \
                else self._fn(args)
            if len(results) != len(batch):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(batch)}")
            for p, r in zip(batch, results):
                p.result = r
                p.event.set()
        except BaseException as e:  # noqa: BLE001
            for p in batch:
                p.error = e
                p.event.set()


# Queues are created lazily in the replica process (a queue holds
# threading primitives, which must not be pickled with the deployment
# definition).  Bound methods store their queue in the owning instance's
# __dict__ so it dies with the replica; bare functions use a module-level
# registry bounded by the number of decorated functions.  The wrapper
# reaches them only through _get_queue — an importable module-level
# function that cloudpickle serializes by reference, keeping the
# lock/registry out of the pickle.
_FN_QUEUES: dict = {}
_QUEUES_LOCK = threading.Lock()
_INSTANCE_ATTR = "_serve_batch_queues"


def _get_queue(self_obj, fn, max_batch_size, batch_wait_timeout_s):
    with _QUEUES_LOCK:
        if self_obj is not None:
            registry = self_obj.__dict__.setdefault(_INSTANCE_ATTR, {})
            key = fn.__qualname__
        else:
            # Keyed by (module, qualname): two same-named functions in
            # different modules must not share one queue (or the second
            # function's requests would be executed by the first).
            registry, key = _FN_QUEUES, (fn.__module__, fn.__qualname__)
        queue = registry.get(key)
        if queue is None:
            queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
            registry[key] = queue
        return queue


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``@serve.batch`` or ``@serve.batch(max_batch_size=...,
    batch_wait_timeout_s=...)``."""

    def wrap(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:   # bound method: (self, request)
                self_obj, arg = args
            else:
                self_obj, arg = None, args[0]
            queue = _get_queue(self_obj, fn, max_batch_size,
                               batch_wait_timeout_s)
            return queue.submit(self_obj, arg)
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
