"""@serve.batch: transparent, latency-aware request batching inside a
replica.

Parity: reference ``python/ray/serve/batching.py`` — concurrent calls
to the decorated method are queued; a flusher invokes the underlying
function ONCE with the list of requests when ``max_batch_size`` is
reached or the flush deadline elapses; each caller gets its own
element of the returned list.  Callers are concurrent actor-thread
requests here (the reference's are asyncio tasks).

Adaptive flush (the serving-under-load lever): instead of a fixed
``batch_wait_timeout_s``, the queue tracks an EWMA of the batch
function's own execution latency and schedules each batch's flush so
the OLDEST pending request completes within the latency budget —
``wait = budget - exec_ewma``.  Under light load batches flush almost
immediately (small batches, low latency); under heavy load the queue
fills to ``max_batch_size`` before the timer fires (large batches, max
throughput) — batch size adapts to offered load with a hard latency
ceiling.  Per-queue batch-size and fill-ratio histograms are exported
at /metrics labelled by deployment (see :func:`set_batch_context`).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu._private.debug.lock_order import diag_lock

# Thread-local batching context: the replica stamps the deployment name
# before invoking user code so flush metrics are labelled per
# deployment (a bare function queue outside a replica reads "driver").
_batch_ctx = threading.local()


def set_batch_context(deployment: Optional[str]) -> None:
    _batch_ctx.deployment = deployment


def _current_deployment() -> str:
    return getattr(_batch_ctx, "deployment", None) or "driver"


class _Pending:
    __slots__ = ("arg", "event", "result", "error", "enqueued_ts",
                 "deployment")

    def __init__(self, arg):
        self.arg = arg
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued_ts = time.monotonic()
        self.deployment = _current_deployment()


class _BatchQueue:
    """One queue per decorated function (per instance for methods).

    ``latency_budget_s`` arms the adaptive flush; when ``None`` the
    fixed ``batch_wait_timeout_s`` is the deadline (reference
    behavior).  Flush scheduling is generation-counted: a timer armed
    for batch generation G flushes ONLY generation G — a full-batch
    flush that races the timer can never early-drain the next batch.
    """

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 latency_budget_s: Optional[float] = None):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._budget = latency_budget_s
        self._lock = diag_lock("serve._BatchQueue._lock")
        self._queue: List[_Pending] = []
        self._generation = 0        # bumped every time the queue drains
        self._timer: Optional[threading.Timer] = None
        self._closed = False
        # EWMA of the batch fn's execution latency (seconds); seeds at
        # zero so the first flush waits the full budget.
        self._exec_ewma = 0.0
        self._ewma_alpha = 0.3
        self.stats = {"flushes": 0, "full_flushes": 0, "timer_flushes": 0,
                      "requests": 0, "errors": 0}

    # -- flush-delay policy ---------------------------------------------
    def _flush_delay(self) -> float:
        if self._budget is None:
            return self._timeout
        # Leave room for the batch's own execution so the oldest
        # request's end-to-end latency stays inside the budget.
        return max(0.0005, self._budget - self._exec_ewma)

    def submit(self, self_obj, arg) -> Any:
        p = _Pending(arg)
        flush_now = False
        with self._lock:
            if self._closed:
                raise RuntimeError("@serve.batch queue is shut down")
            self._queue.append(p)
            self.stats["requests"] += 1
            if len(self._queue) >= self._max:
                flush_now = True
            elif self._timer is None:
                gen = self._generation
                t = threading.Timer(self._flush_delay(), self._timer_flush,
                                    (self_obj, gen))
                t.daemon = True
                self._timer = t
                t.start()
        if flush_now:
            self._flush(self_obj, full=True)
        p.event.wait(timeout=60.0)
        if p.error is not None:
            raise p.error
        return p.result

    def _timer_flush(self, self_obj, gen: int):
        with self._lock:
            if gen != self._generation:
                return          # that batch already flushed full
        self._flush(self_obj, full=False)

    def _take_batch(self) -> List[_Pending]:
        """Drain the queue under the lock; bumps the generation so any
        armed timer for the drained batch becomes a no-op."""
        with self._lock:
            batch, self._queue = self._queue, []
            self._generation += 1
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            return batch

    def _flush(self, self_obj, full: bool):
        batch = self._take_batch()
        if not batch:
            return
        self.stats["flushes"] += 1
        self.stats["full_flushes" if full else "timer_flushes"] += 1
        self._observe_batch(batch)
        started = time.monotonic()
        try:
            args = [p.arg for p in batch]
            results = self._fn(self_obj, args) if self_obj is not None \
                else self._fn(args)
            if len(results) != len(batch):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(batch)}")
            for p, r in zip(batch, results):
                # An Exception element fails ONLY that caller — one bad
                # request in a batch must not poison its neighbors.
                if isinstance(r, BaseException):
                    p.error = r
                    self.stats["errors"] += 1
                else:
                    p.result = r
                p.event.set()
        except BaseException as e:  # noqa: BLE001
            self.stats["errors"] += len(batch)
            for p in batch:
                p.error = e
                p.event.set()
        finally:
            took = time.monotonic() - started
            with self._lock:
                self._exec_ewma = (took if self._exec_ewma == 0.0 else
                                   self._ewma_alpha * took +
                                   (1 - self._ewma_alpha) * self._exec_ewma)

    def _observe_batch(self, batch: List[_Pending]):
        try:
            from ray_tpu._private.metrics_agent import observe_internal
            deployment = batch[0].deployment
            observe_internal(
                "ray_tpu_serve_batch_size", float(len(batch)),
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
                deployment=deployment)
            observe_internal(
                "ray_tpu_serve_batch_fill_ratio",
                len(batch) / max(1, self._max),
                buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
                deployment=deployment)
            oldest_wait = time.monotonic() - batch[0].enqueued_ts
            observe_internal(
                "ray_tpu_serve_batch_wait_seconds", oldest_wait,
                deployment=deployment)
        except Exception as e:   # metrics must never fail a batch
            from ray_tpu._private.debug import swallow
            swallow.noted("serve.batching.metrics", e)

    def close(self):
        """Teardown: fail every pending request loudly instead of
        leaving callers parked on their events for the 60s cap."""
        with self._lock:
            self._closed = True
            pending, self._queue = self._queue, []
            self._generation += 1
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        err = RuntimeError("@serve.batch queue shut down with pending "
                           "requests (replica stopping)")
        for p in pending:
            p.error = err
            p.event.set()


# Queues are created lazily in the replica process (a queue holds
# threading primitives, which must not be pickled with the deployment
# definition).  Bound methods store their queue in the owning instance's
# __dict__ so it dies with the replica; bare functions use a module-level
# registry bounded by the number of decorated functions.  The wrapper
# reaches them only through _get_queue — an importable module-level
# function that cloudpickle serializes by reference, keeping the
# lock/registry out of the pickle.
_FN_QUEUES: dict = {}
_QUEUES_LOCK = diag_lock("serve.batching._QUEUES_LOCK")
_INSTANCE_ATTR = "_serve_batch_queues"


def _get_queue(self_obj, fn, max_batch_size, batch_wait_timeout_s,
               latency_budget_s=None):
    with _QUEUES_LOCK:
        if self_obj is not None:
            registry = self_obj.__dict__.setdefault(_INSTANCE_ATTR, {})
            key = fn.__qualname__
        else:
            # Keyed by (module, qualname): two same-named functions in
            # different modules must not share one queue (or the second
            # function's requests would be executed by the first).
            registry, key = _FN_QUEUES, (fn.__module__, fn.__qualname__)
        queue = registry.get(key)
        if queue is None:
            queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s,
                                latency_budget_s)
            registry[key] = queue
        return queue


def close_instance_queues(self_obj) -> None:
    """Close every batch queue owned by ``self_obj`` (replica
    teardown)."""
    queues = self_obj.__dict__.get(_INSTANCE_ATTR) or {}
    for q in list(queues.values()):
        q.close()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01,
          latency_budget_s: Optional[float] = None):
    """Decorator: ``@serve.batch`` or ``@serve.batch(max_batch_size=...,
    batch_wait_timeout_s=..., latency_budget_s=...)``.

    ``latency_budget_s`` switches the flush deadline from the fixed
    ``batch_wait_timeout_s`` to the adaptive policy: each batch waits
    ``budget - EWMA(exec latency)`` so end-to-end latency of the oldest
    request tracks the budget while batch size grows with load."""

    def wrap(fn: Callable):
        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:   # bound method: (self, request)
                self_obj, arg = args
            else:
                self_obj, arg = None, args[0]
            queue = _get_queue(self_obj, fn, max_batch_size,
                               batch_wait_timeout_s, latency_budget_s)
            return queue.submit(self_obj, arg)
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
