"""User-facing exception hierarchy.

Parity with the reference's ``python/ray/exceptions.py`` (RayError,
RayTaskError wrapping the remote traceback, RayActorError, ObjectLostError,
TaskCancelledError, GetTimeoutError, ...), re-homed for the TPU runtime.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


# Alias matching the reference naming so library code reads the same.
RayError = RayTpuError


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Wraps the remote exception plus its traceback string; re-raised at
    ``get`` on the caller side (reference: exceptions.py RayTaskError).
    """

    def __init__(self, cause: BaseException, task_desc: str = "",
                 tb: str | None = None):
        self.cause = cause
        self.task_desc = task_desc
        self.traceback_str = tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__))
        # The formatted string above is the durable record; drop the frame
        # chain — stored error objects otherwise pin the executor's and the
        # user function's locals (including deserialized arg refs) for as
        # long as the error is retrievable (reference: RayTaskError ships
        # text, never traceback objects).
        cause.__traceback__ = None
        super().__init__(
            f"Task {task_desc} failed:\n{self.traceback_str}")

    def __reduce__(self):
        # Default Exception pickling would re-run __init__ with the
        # formatted message as ``cause``; preserve the real fields so the
        # error survives the process-worker / multi-host wire.
        return (TaskError, (self.cause, self.task_desc, self.traceback_str))

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is an instance of the cause's class so
        ``except UserError`` works across the task boundary."""
        cause_cls = type(self.cause)
        if cause_cls is TaskError:
            return self.cause
        try:
            err = cause_cls(*getattr(self.cause, "args", ()))
            err.__cause__ = self
            return err
        except Exception:
            return self


RayTaskError = TaskError


class ActorError(RayTpuError):
    """Actor died before/while executing a method (reference: RayActorError)."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(f"Actor {actor_id} unavailable: {reason}")


RayActorError = ActorError


class ActorDiedError(ActorError):
    pass


class ObjectLostError(RayTpuError):
    """Object's value was lost (all copies gone, lineage exhausted)."""

    def __init__(self, object_id=None, reason: str = "object lost"):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost: {reason}")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    def __init__(self, object_id=None):
        super().__init__(object_id, "owner died")


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    def __init__(self, node_id=None):
        self.node_id = node_id
        super().__init__(f"Node {node_id} died")


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass
