"""The per-node daemon: scheduler + worker pool + object store.

Parity: reference ``src/ray/raylet/node_manager.cc`` (NodeManager implements
the NodeManagerService: RequestWorkerLease/ReturnWorker (:1629), PG bundle
2PC, periodic ``ScheduleAndDispatchTasks`` tick (:392-394), debug dump) and
``src/ray/raylet/main.cc`` (raylet process = plasma store in-process +
NodeManager).  Here a Raylet is an in-process object with its own event loop
and worker threads; the lease/return/2PC surface is identical so a gRPC
transport can be slotted in front of it for multi-host deployments.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.cluster_task_manager import ClusterTaskManager
from ray_tpu._private.event_loop import EventLoop
from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu._private.local_object_manager import LocalObjectManager
from ray_tpu._private.local_task_manager import LocalTaskManager
from ray_tpu._private.object_manager import NodeObjectManager
from ray_tpu._private.object_store import NodeObjectStore
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu._private.worker_pool import WorkerPool
from ray_tpu.scheduler.bundle_packing import bundle_resource_names
from ray_tpu.scheduler.resources import (
    ClusterResourceView, NodeResources, ResourceRequest, _quantize)


class Raylet:
    def __init__(self, cluster, resources: Dict[str, float],
                 node_name: str = "", labels: Optional[Dict] = None,
                 object_store_memory: Optional[int] = None):
        cfg = get_config()
        self.cluster = cluster
        self.node_id = NodeID.from_random()
        self.node_name = node_name or f"node-{self.node_id.hex()[:8]}"
        #: Monotonic registration incarnation, minted by the GCS node
        #: manager at register time (incarnation fencing).  None until
        #: registered; preserved across GCS restarts via reconcile.
        self.incarnation: Optional[int] = None
        self.local_resources = NodeResources(resources, labels=labels)
        self.cluster_view = ClusterResourceView()   # local (dirty) view
        self.loop = EventLoop(f"raylet-{self.node_id.hex()[:6]}")
        store_capacity = object_store_memory or cfg.object_store_memory
        spill_dir = f"{cfg.temp_dir}/spill/{self.node_id.hex()[:8]}"
        self.object_store = NodeObjectStore(
            self.node_id,
            store_capacity,
            spill_dir=spill_dir,
            spill_threshold=cfg.object_spilling_threshold,
            native_backend=_maybe_native_store(cfg, store_capacity),
            on_spilled=self._record_spilled_url)
        # Async spill IO thread (local_object_manager parity): moves
        # over-threshold spilling off the put path and feeds the
        # create-request queue.
        self.local_object_manager = LocalObjectManager(
            self.object_store, spill_dir,
            node_label=self.node_id.hex()[:12])
        self.object_store.attach_spill_manager(self.local_object_manager)
        self.worker_pool = WorkerPool(self)
        self.local_task_manager = LocalTaskManager(self)
        self.cluster_task_manager = ClusterTaskManager(self)
        self.object_manager = NodeObjectManager(self, cluster.object_directory)
        self.core_worker = None      # wired by the cluster/driver
        # Lease-protocol round-trip counters (plain bumps on the hot
        # path, rendered by the tick collector): the dispatch fast
        # path's "a 500-task burst costs dozens of RPCs, not 500" claim
        # is asserted against lease_requests + lease_batch_requests.
        self.lease_stats = {"lease_requests": 0,
                            "lease_batch_requests": 0,
                            "lease_batch_entries": 0}
        self._dead = False
        self._host_stats = None
        self._host_stats_ts = 0.0
        # Bundles: (pg_id, idx) -> ResourceRequest, prepared or committed.
        self._prepared_bundles: Dict = {}
        self._committed_bundles: Dict = {}
        # Periodic scheduling tick (node_manager.cc:392-394).
        self.loop.schedule_every(cfg.event_loop_tick_ms / 1000.0,
                                 self.cluster_task_manager.schedule_and_dispatch,
                                 "raylet.schedule_tick")
        # Heartbeats to GCS on a DEDICATED thread: the event loop runs
        # callbacks serially, so one long callback (a big serialization,
        # a compile) would delay beats behind it and a loaded box could
        # miss num_heartbeats_timeout in a row — a false node death.
        # The reference raylet also heartbeats off its main dispatch
        # path (gcs_heartbeat_manager.h).
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            args=(cfg.raylet_heartbeat_period_milliseconds / 1000.0,),
            daemon=True,
            name=f"ray_tpu::hb::{self.node_id.hex()[:6]}")
        self._hb_thread.start()
        # Seed own view.
        self.cluster_view.add_node(self.node_id, self.local_resources)

    # ---- GCS-facing -----------------------------------------------------
    def node_info(self) -> dict:
        return {
            "node_id": self.node_id.hex(),
            "node_name": self.node_name,
            "alive": True,
            "resources": self.local_resources.to_float_dict("total"),
            "labels": dict(self.local_resources.labels),
        }

    def get_resource_report(self) -> dict:
        report = {
            "available": self.local_resources.to_float_dict("available"),
            "total": self.local_resources.to_float_dict("total"),
            "load": {"queued": self.cluster_task_manager.num_queued(),
                     "dispatch": self.local_task_manager.num_queued()},
            # Outbound-transfer load (sessions/queue/in-flight bytes):
            # the head folds this into directory answers so pullers can
            # spread across the least-loaded sources (load-aware source
            # selection for collective broadcasts).
            "transfer_load":
                self.object_store.transfer_ledger.load_snapshot(),
        }
        # Physical stats ride the report the node already sends
        # (reference: reporter agent -> GCS), throttled to ~1 Hz.
        import time as time_mod
        now = time_mod.monotonic()
        if now - self._host_stats_ts >= 1.0:
            try:
                from ray_tpu.dashboard.reporter import collect_host_stats
                self._host_stats = collect_host_stats()
                self._host_stats_ts = now
            except Exception:
                pass
        if self._host_stats is not None:
            report["host_stats"] = self._host_stats
        return report

    def update_resource_usage(self, batch: dict):
        """Apply the GCS broadcast to the local (dirty) view
        (grpc_based_resource_broadcaster parity).

        Batch format: ``{"rows": {node_id: usage}, "full": bool,
        "removed": [node_id]}`` — a DELTA upserts its rows only; a FULL
        snapshot additionally prunes nodes absent from it; explicit
        removals (node death/dereg) arrive in ``removed`` so deltas
        never have to enumerate the whole membership
        (ray_syncer.h:37-66)."""
        if self._dead:
            return
        rows = batch.get("rows", batch)     # legacy plain-dict = full
        is_full = batch.get("full", "rows" not in batch)
        removed = batch.get("removed", ())
        known = set(self.cluster_view.node_ids())
        for node_id, usage in rows.items():
            if node_id == self.node_id:
                continue
            if node_id not in known:
                nr = NodeResources(usage["total"])
                nr.available = {k: _quantize(v)
                                for k, v in usage["available"].items()}
                self.cluster_view.add_node(node_id, nr)
                self.cluster_task_manager.on_cluster_changed()
            else:
                self.cluster_view.update_available(node_id,
                                                   usage["available"])
        gone = set(removed) & known
        if is_full:
            gone |= known - set(rows.keys()) - {self.node_id}
        for node_id in gone:
            self.cluster_view.remove_node(node_id)
        # Suspect membership (suspect-before-dead): mask those nodes in
        # the local scheduling view — no NEW placements there until
        # their beats resume.  Includes self: a node the GCS suspects
        # (e.g. its outbound link is cut) stops self-placing too.
        suspect = batch.get("suspect")
        if suspect is not None:
            self.cluster_view.set_masked(set(suspect))
        self.cluster_task_manager.on_cluster_changed()

    def _record_spilled_url(self, object_id, url: str):
        """Spill callback: record the spilled_url with the owner's
        reference counter (the reconstruction/debug surface the
        reference keeps in the ObjectDirectory/owner table).

        Posted to the event loop, never taken inline: the store invokes
        this callback while HOLDING its lock, and the reference
        counter's delete path runs its subscribers (which take the
        store lock) while holding the refcount lock — recording
        inline would be an ABBA deadlock between a spill publish and a
        concurrent last-ref drop."""
        core = self.core_worker or self.cluster.core_worker
        if core is None:
            return

        def record():
            try:
                core.reference_counter.set_spilled_url(object_id, url)
            except Exception as e:
                # A lost spilled_url silently breaks restore-from-disk
                # for this object later — count it (graftcheck R7).
                from ray_tpu._private.debug import swallow
                swallow.noted("raylet.record_spilled_url", e)
        self.loop.post(record, "raylet.record_spilled_url")

    def _heartbeat(self):
        if not self._dead:
            # Chaos point: an injected error/delay here simulates a
            # partitioned or wedged node (missed beats -> declared
            # dead) without killing the process.  ctx carries the node
            # so in-process multi-node tests can cut ONE node's beats.
            fault_injection.hook("node.heartbeat",
                                 node=self.node_id.hex()[:12])
            self.cluster.gcs.heartbeat_manager.heartbeat(self.node_id)

    def _heartbeat_loop(self, period_s: float):
        import time as time_mod

        from ray_tpu._private.debug import swallow
        while not self._dead:
            try:
                self._heartbeat()
            except Exception as e:
                # The sender must survive a flapping GCS link, but a
                # silently-failing heartbeat loop looks exactly like a
                # healthy one until the node is declared dead —
                # count/log it (graftcheck R7).
                swallow.noted("raylet.heartbeat", e)
            time_mod.sleep(period_s)

    # ---- lease protocol (NodeManagerService) ----------------------------
    def request_worker_lease(self, spec: TaskSpec, reply: Callable):
        """HandleRequestWorkerLease (node_manager.cc:1629)."""
        if self._dead:
            reply({"rejected": True, "reason": "node dead"})
            return
        self.lease_stats["lease_requests"] += 1
        self.cluster_task_manager.queue_and_schedule(spec, reply)

    def request_worker_lease_batch(self, specs, reply: Callable):
        """Batched HandleRequestWorkerLease: lease up to len(specs)
        workers of one scheduling class in ONE round-trip.  ``reply``
        fires once with ``{"results": [...]}`` ordered like ``specs``;
        each result is a grant (``worker``/``raylet``), a spillback
        (``retry_at``), a rejection, or ``backlog`` (feasible but no
        capacity this tick — the submitter keeps the task and re-pumps;
        with ``infeasible: True`` it re-leases through the single-lease
        path, which parks raylet-side until the cluster changes)."""
        if self._dead:
            reply({"results": [{"rejected": True, "reason": "node dead"}
                               for _ in specs]})
            return
        self.lease_stats["lease_batch_requests"] += 1
        self.lease_stats["lease_batch_entries"] += len(specs)
        try:
            # Chaos point: bounce a WHOLE batch (the submitter must
            # fall back to single leases without burning task retries).
            fault_injection.hook("worker.lease_batch")
        except Exception as e:
            reply({"results": [{"rejected": True, "batch_fault": True,
                                "reason": f"lease batch fault: {e}"}
                               for _ in specs]})
            return
        self.cluster_task_manager.queue_and_schedule_batch(specs, reply)

    def return_worker(self, worker, disconnect: bool = False):
        """HandleReturnWorker: release lease + resources."""
        self.local_task_manager.release_worker_resources(worker)
        if disconnect:
            worker.stop()
        else:
            self.worker_pool.push_worker(worker)
        # A freed worker slot may unblock the dispatch queue.
        self.loop.post(self.local_task_manager.dispatch, "local.dispatch")

    def on_actor_worker_exit(self, actor_id, worker_id):
        self.local_task_manager.release_worker_resources(
            _WorkerIdHolder(worker_id))
        self.cluster.gcs.actor_manager.on_actor_worker_died(
            actor_id, "worker exited")

    # ---- placement group 2PC (node_manager.proto:319-330) ---------------
    def prepare_bundle_resources(self, pg_id: PlacementGroupID, idx: int,
                                 req: ResourceRequest) -> bool:
        if self._dead:
            return False
        if (pg_id, idx) in self._prepared_bundles or \
                (pg_id, idx) in self._committed_bundles:
            return True
        if not self.local_resources.allocate(req):
            return False
        self._prepared_bundles[(pg_id, idx)] = req
        return True

    def commit_bundle_resources(self, pg_id: PlacementGroupID, idx: int,
                                req: ResourceRequest):
        self._prepared_bundles.pop((pg_id, idx), None)
        self._committed_bundles[(pg_id, idx)] = req
        # Add the formatted PG resources to this node (bundle_spec.h).
        formatted = bundle_resource_names(pg_id, idx, req)
        for name, amount in formatted.items():
            q = _quantize(amount)
            self.local_resources.total[name] = \
                self.local_resources.total.get(name, 0) + q
            self.local_resources.available[name] = \
                self.local_resources.available.get(name, 0) + q
        self.cluster_view.update_node(self.node_id, self.local_resources)
        self.cluster_task_manager.on_cluster_changed()

    def cancel_resource_reserve(self, pg_id: PlacementGroupID, idx: int):
        req = self._prepared_bundles.pop((pg_id, idx), None)
        if req is not None:
            self.local_resources.release(req)
            return
        req = self._committed_bundles.pop((pg_id, idx), None)
        if req is None:
            return
        formatted = bundle_resource_names(pg_id, idx, req)
        for name, amount in formatted.items():
            q = _quantize(amount)
            self.local_resources.total[name] = max(
                0, self.local_resources.total.get(name, 0) - q)
            self.local_resources.available[name] = max(
                0, self.local_resources.available.get(name, 0) - q)
            if self.local_resources.total.get(name) == 0:
                self.local_resources.total.pop(name, None)
                self.local_resources.available.pop(name, None)
        self.local_resources.release(req)
        self.cluster_view.update_node(self.node_id, self.local_resources)

    # ---- lifecycle ------------------------------------------------------
    def kill(self):
        """Simulated hard node death (chaos testing: NodeKillerActor
        parity) — stops heartbeating and drops all state."""
        self._dead = True
        self.worker_pool.shutdown()
        self.object_manager.stop()
        self.local_object_manager.stop()
        self.loop.stop()

    def shutdown(self):
        if self._dead:
            return
        self._dead = True
        self.cluster.gcs.unregister_raylet(self.node_id)
        self.worker_pool.shutdown()
        self.object_manager.stop()
        self.local_object_manager.stop()
        self.loop.stop()

    def debug_string(self) -> str:
        return (f"Raylet {self.node_name} ({self.node_id.hex()[:8]}): "
                f"res={self.local_resources.to_float_dict('available')} "
                f"queues={self.cluster_task_manager.debug_state()} "
                f"workers={self.worker_pool.num_total()} "
                f"objects={self.object_store.num_objects()}")


class _WorkerIdHolder:
    __slots__ = ("worker_id",)

    def __init__(self, worker_id):
        self.worker_id = worker_id


_native_store_failed = False


def _maybe_native_store(cfg, capacity_bytes: int = 0):
    """Load the native C++ shm store if built (ray_tpu/native).

    The segment is sized to the node store's capacity (clamped to the
    free space actually available on /dev/shm): a segment smaller than
    the store forced every large put onto the python-held fallback path
    — and through its extra flatten copy (ENVELOPE_r05's 1.44 GB/s put).
    tmpfs pages are allocated on first touch, so an over-provisioned
    segment costs nothing until objects actually land in it."""
    global _native_store_failed
    if not cfg.use_native_object_store or _native_store_failed:
        return None
    capacity = capacity_bytes or cfg.object_store_memory
    try:
        from ray_tpu.native import shm_store
    except Exception:
        _native_store_failed = True
        return None
    try:
        import shutil
        # tmpfs pages are first-touch, so df-free does not reflect other
        # open sparse segments; subtract this process's outstanding
        # reservations and keep a 4x headroom for sibling processes —
        # over-committed segments die with SIGBUS when filled, not with
        # a catchable error.
        shm_free = shutil.disk_usage("/dev/shm").free \
            - shm_store.reserved_bytes()
        capacity = max(64 * 1024 * 1024, min(capacity, shm_free // 4))
    except Exception:
        pass
    try:
        return shm_store.open_store(capacity=capacity)
    except Exception:
        _native_store_failed = True
        return None
