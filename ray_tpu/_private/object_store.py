"""Per-process and per-node object stores.

Parity targets:
  * ``CoreWorkerMemoryStore`` (reference
    ``src/ray/core_worker/store_provider/memory_store/``) — in-process store
    for small objects and pending futures; blocking ``Get`` with timeout.
  * Plasma (reference ``src/ray/object_manager/plasma/`` — shared-memory store
    with capacity accounting, pinning, LRU eviction and spill-to-disk via
    ``raylet/local_object_manager.cc``).  Here :class:`NodeObjectStore` is the
    plasma equivalent: host-memory slab per node, optional native C++
    shared-memory backend (``ray_tpu/native``), spill/restore to the session
    dir, and a **device-object extension** the reference never had — jax
    device buffers can live in the store without a host copy and are only
    materialized to host when crossing nodes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ray_tpu import exceptions
from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject, deserialize
from ray_tpu._private.debug import diag_condition, flight_recorder

try:
    from ray_tpu.native import shm_store as _shm
except Exception:  # pragma: no cover — native backend absent entirely
    _shm = None


def _spill_url(path: str, offset: int, size: int) -> str:
    """Spill location record: fused batch files hold many objects, so a
    bare path is not enough — reference ``spilled_url`` carries
    ``?offset=&size=`` exactly like this."""
    return f"{path}?offset={offset}&size={size}"


def _parse_spill_url(url: str) -> Tuple[str, int, int]:
    path, _, query = url.partition("?")
    offset = size = 0
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == "offset":
            offset = int(v)
        elif k == "size":
            size = int(v)
    return path, offset, size


class DeviceObject:
    """A store entry whose payload is a jax device array (or pytree).

    Zero-copy handoff: actors on the same node exchange the device buffer
    directly; a host copy happens only on spill or cross-node transfer.
    This is the TPU-native extension of plasma (SURVEY.md §7 "hard parts").
    """

    __slots__ = ("value", "nbytes")

    def __init__(self, value):
        import jax
        self.value = value
        self.nbytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(value)
            if hasattr(x, "dtype"))

    def to_serialized(self) -> SerializedObject:
        from ray_tpu._private.serialization import serialize
        return serialize(self.value)


class _Entry:
    __slots__ = ("data", "error", "size", "pin_count", "last_access",
                 "spilled_path", "sealed", "is_device", "primary",
                 "spilling")

    def __init__(self, data=None, error=None, size=0):
        self.data = data              # SerializedObject | DeviceObject | None
        self.error = error            # Exception to raise at get()
        self.size = size
        # READER pins only (executor arg reads, shm_locate clients):
        # a reader-pinned entry is never spilled out from under the
        # read.  Primary copies ARE spillable (that is the whole point
        # of spilling; reference local_object_manager spills pinned
        # primary copies and records the URL), which is why the owner's
        # primary-copy claim is this separate flag and not a pin.
        # Nothing gates on ``primary`` yet — it is bookkeeping for the
        # owner-copy semantics replacing the old put-time pin.
        self.pin_count = 0
        self.primary = False
        # An async spill has copied-out/is copying this entry's bytes;
        # guards double-selection (the delete path still wins).
        self.spilling = False
        self.last_access = time.monotonic()
        #: Spill location URL (``path?offset=&size=``) once on disk.
        self.spilled_path: Optional[str] = None
        self.sealed = data is not None or error is not None
        self.is_device = isinstance(data, DeviceObject)


class MemoryStore:
    """In-process store: small objects, error markers, pending futures.

    ``get`` blocks on a condition variable until the object is sealed
    (reference: memory store ``GetAsync``/``Get`` with timeout).
    """

    def __init__(self):
        self._lock = diag_condition(name="MemoryStore._lock")
        self._entries: Dict[ObjectID, _Entry] = {}
        self._get_callbacks: Dict[ObjectID, list] = {}

    def put(self, object_id: ObjectID, data, error=None) -> int:
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.sealed:
                return entry.size  # idempotent re-put
            entry = _Entry(data=data, error=error, size=size)
            self._entries[object_id] = entry
            callbacks = self._get_callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(entry)
        return size

    def put_error(self, object_id: ObjectID, error: BaseException):
        self.put(object_id, None, error=error)

    def fail(self, object_id: ObjectID, error: BaseException):
        """Force-seal ``error`` over the entry, REPLACING any existing
        value (owner-death semantics: the owner's table was
        authoritative, so its loss invalidates the object even when
        bytes still exist somewhere — borrowers must observe the error,
        reference: OWNER_DIED reply on Get)."""
        with self._lock:
            entry = _Entry(data=None, error=error)
            self._entries[object_id] = entry
            callbacks = self._get_callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(entry)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> _Entry:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                e = self._entries.get(object_id)
                if e is not None and e.sealed:
                    e.last_access = time.monotonic()
                    return e
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise exceptions.GetTimeoutError(
                        f"Get timed out for {object_id}")
                self._lock.wait(timeout=remaining if remaining is None
                                else min(remaining, 0.5))

    def get_async(self, object_id: ObjectID, cb: Callable[[_Entry], None]):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed:
                pass
            else:
                self._get_callbacks.setdefault(object_id, []).append(cb)
                return
        cb(e)

    def cancel_get_async(self, object_id: ObjectID,
                         cb: Callable[[_Entry], None]):
        """Deregister a pending get_async callback (no-op if it already
        fired) — callers that stop waiting must not leak closures."""
        with self._lock:
            cbs = self._get_callbacks.get(object_id)
            if cbs is None:
                return
            try:
                cbs.remove(cb)
            except ValueError:
                return
            if not cbs:
                del self._get_callbacks[object_id]

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._entries.pop(object_id, None)
            self._get_callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


class TransferLedger:
    """Sender-side outbound-transfer accounting for ONE store: active
    sessions, in-flight bytes, and a FIFO overflow queue (transfer
    admission — push_manager.cc's bounded concurrent sends, made a
    per-store budget).  Both outbound legs share it: chunk sessions a
    ChunkServer admits for remote pullers, and in-process
    store-to-store copies.  Gauges land in the owning store's ``stats``
    dict so they ride the existing /metrics collector and
    ``ray-tpu memory``.

    The condition is a LEAF lock: nothing else is ever acquired under
    it, so any thread (RPC handlers, pull pools) may block in
    ``try_acquire`` safely.
    """

    __slots__ = ("_cond", "_active", "_inflight", "_queue", "stats")

    def __init__(self, stats: dict):
        self._cond = diag_condition(name="TransferLedger._cond")
        self._active = 0
        self._inflight = 0
        self._queue: list = []        # FIFO of waiter tokens
        self.stats = stats
        for key in ("outbound_sessions_active", "outbound_inflight_bytes",
                    "transfer_admission_queue_depth",
                    "transfer_admission_waits",
                    "outbound_served_bytes", "relay_served_bytes"):
            stats.setdefault(key, 0)

    def _sync_gauges_locked(self) -> None:
        self.stats["outbound_sessions_active"] = self._active
        self.stats["outbound_inflight_bytes"] = self._inflight
        self.stats["transfer_admission_queue_depth"] = len(self._queue)

    def enqueue(self) -> object:
        """Join the FIFO admission queue; returns a ticket that KEEPS
        its position across bounded ``wait_grant`` polls (a waiter that
        probes for better sources between polls must not forfeit its
        turn).  Pair with ``wait_grant``/``cancel``."""
        token = object()
        with self._cond:
            self._queue.append(token)
            self._sync_gauges_locked()
            if len(self._queue) > 1 or self._active >= max(
                    1, get_config().object_transfer_max_outbound_sessions):
                self.stats["transfer_admission_waits"] += 1
        return token

    def wait_grant(self, token, timeout: Optional[float] = None,
                   nbytes: int = 0) -> bool:
        """Bounded wait for ``token`` to reach the queue head with a
        free slot.  False on timeout — the ticket KEEPS its position
        (call again, or ``cancel`` to leave the queue)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                cap = max(1, get_config()
                          .object_transfer_max_outbound_sessions)
                if self._queue and self._queue[0] is token and \
                        self._active < cap:
                    self._queue.pop(0)
                    self._active += 1
                    self._inflight += int(nbytes)
                    self._sync_gauges_locked()
                    # The pop changed who is head: with cap > 1 the
                    # next waiter may be grantable NOW — wake it
                    # instead of letting it ride the 0.2 s poll.
                    self._cond.notify_all()
                    return True
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(remaining, 0.2))

    def cancel(self, token) -> None:
        """Leave the queue without a grant (timeout / re-selection)."""
        with self._cond:
            if token in self._queue:
                self._queue.remove(token)
                self._sync_gauges_locked()
                # The head of the queue may have become grantable.
                self._cond.notify_all()

    def try_acquire(self, nbytes: int = 0,
                    timeout: Optional[float] = None) -> bool:
        """FIFO slot acquisition; True on grant.  A timeout leaves the
        queue (False) — the caller replies busy / re-selects another
        source.  ``timeout=None`` waits indefinitely (in-process pulls
        bound the wait with their own deadline)."""
        token = self.enqueue()
        if self.wait_grant(token, timeout=timeout, nbytes=nbytes):
            return True
        self.cancel(token)
        return False

    def release(self, nbytes: int = 0) -> None:
        with self._cond:
            self._active = max(0, self._active - 1)
            self._inflight = max(0, self._inflight - int(nbytes))
            self._sync_gauges_locked()
            self._cond.notify_all()

    def note_served(self, nbytes: int, relay: bool = False) -> None:
        with self._cond:
            self.stats["outbound_served_bytes"] += int(nbytes)
            if relay:
                self.stats["relay_served_bytes"] += int(nbytes)

    def load_score(self) -> Tuple[int, int]:
        """(sessions incl. queued, in-flight bytes) — the live signal
        load-aware source selection ranks candidates by."""
        with self._cond:
            return (self._active + len(self._queue), self._inflight)

    def has_free_slot(self) -> bool:
        with self._cond:
            cap = max(1, get_config()
                      .object_transfer_max_outbound_sessions)
            return not self._queue and self._active < cap

    def load_snapshot(self) -> dict:
        """Wire form for resource reports (head-side load hints)."""
        with self._cond:
            return {"active": self._active, "queued": len(self._queue),
                    "inflight_bytes": self._inflight}


class _PartialTransfer:
    """Relay surface over ONE in-flight transfer writer: tracks the
    contiguous assembly watermark and serves prefix reads to downstream
    pullers while the upstream chunks are still landing — the chain
    half of the collective broadcast path.

    Lifecycle: registered by the transfer writer (the single-writer
    guarantee means at most one per (object, store)), advanced per
    landed chunk, quiesced+promoted at seal (later reads go through the
    sealed entry) or failed at abort (readers get None and re-select a
    different source).

    Safety: the prefix memcpy runs OUTSIDE the condition under a
    reader count; seal/abort wait for readers to drain BEFORE the
    backing block is sealed-registered/deleted, so a relay read can
    never observe recycled bytes.  A read never crosses the watermark —
    no torn chunks.  The condition is a leaf from the reader side
    (readers touch no store lock while holding it)."""

    __slots__ = ("store", "object_id", "nbytes", "_cond", "_watermark",
                 "_ooo", "_readers", "_failed", "_sealing", "_sealed",
                 "_read_raw", "_raw_after_seal", "_sealed_cache")

    def __init__(self, store: "NodeObjectStore", object_id: ObjectID,
                 nbytes: int, read_raw):
        self.store = store
        self.object_id = object_id
        self.nbytes = nbytes
        self._cond = diag_condition(name="_PartialTransfer._cond")
        self._watermark = 0
        self._ooo: Dict[int, int] = {}   # offset -> end, out-of-order
        self._readers = 0
        self._failed = False
        self._sealing = False
        self._sealed = False
        self._read_raw = read_raw        # (start, end) -> buffer view
        # Heap-backed writers keep their raw buffer valid past seal
        # (nothing ever recycles a private bytearray): tail relay reads
        # stay O(chunk) instead of re-materializing via the store.
        self._raw_after_seal = False
        # One-time flat materialization for sealed entries with no
        # O(chunk) read surface (python-held winner of a put race) —
        # without it every tail chunk would re-flatten the whole
        # object.
        self._sealed_cache: Optional[bytes] = None

    # ---- writer side ---------------------------------------------------
    def advance(self, offset: int, length: int) -> None:
        """A chunk landed at [offset, offset+length): extend the
        contiguous watermark (the chunk pipeline assembles in order, so
        the out-of-order stash is almost always empty)."""
        with self._cond:
            self._ooo[offset] = offset + length
            while self._watermark in self._ooo:
                self._watermark = self._ooo.pop(self._watermark)
            self._cond.notify_all()

    def quiesce_for_seal(self) -> None:
        """Stop raw-view reads and drain in-flight ones — called BEFORE
        the backing block is sealed/registered, after which eviction
        could recycle it under a raw read.  Reads arriving during the
        window time out ``pending`` and retry into the sealed path."""
        with self._cond:
            self._sealing = True
            self._cond.notify_all()
            while self._readers:
                self._cond.wait(timeout=0.1)

    def mark_sealed(self, raw_still_valid: bool = False) -> None:
        """Promote to sealed.  ``raw_still_valid`` says the raw buffer
        the reads ran against cannot be recycled (heap bytearray, kept
        alive by the read closure) — tail relay reads keep using it
        directly instead of round-tripping through the store entry."""
        with self._cond:
            self._sealed = True
            self._raw_after_seal = raw_still_valid
            if not raw_still_valid:
                # Post-seal reads resolve through the store entry; drop
                # the raw view so sessions can't pin it needlessly.
                self._read_raw = None
            self._sealing = False
            self._watermark = self.nbytes
            self._cond.notify_all()

    def mark_failed(self) -> None:
        """Upstream transfer died (abort/failed seal): fail downstream
        relay readers cleanly and drain any raw read before the caller
        recycles the backing block.  The raw-read closure is dropped —
        lingering relay sessions must not keep a dead transfer's
        buffer alive until their TTL."""
        with self._cond:
            self._failed = True
            self._read_raw = None
            self._cond.notify_all()
            while self._readers:
                self._cond.wait(timeout=0.1)

    @property
    def watermark(self) -> int:
        with self._cond:
            return self._watermark

    @property
    def failed(self) -> bool:
        with self._cond:
            return self._failed

    # ---- reader side (relay sessions) ----------------------------------
    def read_range(self, start: int, end: int,
                   timeout: Optional[float] = None):
        """Bytes of ``[start, end)`` once the watermark covers them.
        Raises TimeoutError while the range is still being assembled
        (the receiver re-requests that chunk); returns None when the
        upstream transfer failed (the receiver re-selects another
        source)."""
        fault_injection.hook("transfer.relay")
        end = min(end, self.nbytes)
        deadline = None if timeout is None else time.monotonic() + timeout
        raw = None
        with self._cond:
            while True:
                if self._failed:
                    return None
                if self._sealed:
                    if self._raw_after_seal:
                        # Un-recyclable raw buffer: serve directly, no
                        # reader accounting needed post-seal.
                        return bytes(self._read_raw(start, end))
                    break
                if self._watermark >= end and not self._sealing:
                    self._readers += 1
                    # Capture under the condition: mark_failed nulls
                    # the closure, but only after readers drain.
                    raw = self._read_raw
                    break
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"relay watermark {self._watermark} < {end}")
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(remaining, 0.2))
        if raw is not None:
            try:
                return bytes(raw(start, end))
            finally:
                with self._cond:
                    self._readers -= 1
                    self._cond.notify_all()
        # Sealed: the bytes live in the store entry now (reads go
        # through a native pin / the spill mmap, eviction-safe).
        data = self.store.read_sealed_range(self.object_id, start, end)
        if data is not None:
            return data
        # No O(chunk) surface (a python-held put won the
        # materialization race): flatten ONCE, cache, slice — tail
        # relay reads stay linear in object size overall.
        with self._cond:
            blob = self._sealed_cache
        if blob is None:
            serialized = self.store.get_serialized(self.object_id)
            if serialized is None:
                return None
            blob = serialized.to_bytes()
            with self._cond:
                self._sealed_cache = blob
        return blob[start:end]


def partial_chunk_source(store: Optional["NodeObjectStore"]):
    """``get_partial`` hook for :class:`ray_tpu.rpc.chunked.ChunkServer`:
    serve the assembled prefix of an in-flight transfer to downstream
    pullers (chunk-level relay) when no sealed copy exists yet."""

    def get_partial(oid_bin: bytes):
        if store is None:
            return None
        return store.open_relay_source(ObjectID(oid_bin))

    return get_partial


class NodeObjectStore:
    """Plasma-equivalent per-node store with capacity, pinning and spilling.

    Reference behaviors kept: create/seal lifecycle, primary-copy pinning
    (``local_object_manager.h:37``), spill-over-threshold with batched
    writes, restore-on-demand, delete-when-out-of-scope, fallback allocation
    never fails hard (OOM raises only if spilling cannot reclaim).
    """

    def __init__(self, node_id, capacity_bytes: int, spill_dir: str,
                 spill_threshold: float = 0.8, native_backend=None,
                 on_spilled: Optional[Callable] = None):
        self.node_id = node_id
        self.capacity = capacity_bytes
        self.spill_threshold = spill_threshold
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._lock = diag_condition(name="NodeObjectStore._lock")
        self._entries: Dict[ObjectID, _Entry] = {}
        self._used = 0
        # Bytes reserved by in-flight transfer writers (charged before
        # the chunks land so concurrent pulls cannot over-commit the
        # budget; moved into _used at seal, dropped at abort).
        self._transfer_reserved = 0
        # Objects with an in-flight transfer writer: the source-level
        # fix for the double-writer native-delete race — concurrent
        # pulls of one object (raylet pull path + node-host executor
        # fetch, or two peers racing) are deduped HERE so at most one
        # transfer writer ever exists per (object, store); later
        # callers wait for the winner and adopt its sealed copy.
        self._active_transfers: set = set()
        # In-flight transfers relayable to downstream pullers (chunk
        # relay): object -> _PartialTransfer.  At most one per object
        # (rides the single-writer claim above).
        self._partials: Dict[ObjectID, _PartialTransfer] = {}
        self._native = native_backend  # ray_tpu.native shm store, optional
        # Create-request queue state (create_request_queue.h parity):
        # over-capacity reservations wait on the store condition and are
        # retried as deletes/spills free space; depth is a live gauge.
        self._create_waiters = 0
        # Async spill manager (LocalObjectManager), attached by the
        # raylet; stores constructed bare still spill inline.
        self._spill_manager = None
        #: ``on_spilled(object_id, url)`` — owner-side spilled_url
        #: recording (reference_counter), wired by the raylet.
        self._on_spilled = on_spilled
        # Live objects per spill file: fused batch files are unlinked
        # only once every object they hold is deleted.
        self._spill_files: Dict[str, set] = {}
        self.stats = {"spilled_bytes": 0, "restored_bytes": 0,
                      "spilled_objects": 0, "restored_objects": 0,
                      "evicted_objects": 0, "native_put_bytes": 0,
                      "native_puts": 0, "queued_creates": 0,
                      "create_queue_wait_ms": 0.0,
                      "create_queue_timeouts": 0, "spill_errors": 0}
        # Outbound transfer admission + accounting (sender side of the
        # collective broadcast path); gauges live in self.stats.
        self.transfer_ledger = TransferLedger(self.stats)
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        nid = getattr(node_id, "hex", lambda: str(node_id))()[:12]

        def _collect(store):
            labels = {"node": nid}
            record_internal("ray_tpu.object_store.used_bytes",
                            store._used, **labels)
            record_internal("ray_tpu.object_store.capacity_bytes",
                            store.capacity, **labels)
            record_internal("ray_tpu.object_store.num_objects",
                            len(store._entries), **labels)
            record_internal("ray_tpu.object_store.create_queue_depth",
                            store._create_waiters, **labels)
            for k, v in store.stats.items():
                record_internal(f"ray_tpu.object_store.{k}", v, **labels)
        get_metrics_registry().register_collector(self, _collect)

    def attach_spill_manager(self, manager) -> None:
        """Wire the raylet's LocalObjectManager: over-threshold spilling
        moves off the put path onto its io thread, and queued creates
        kick it instead of spilling inline."""
        with self._lock:
            self._spill_manager = manager

    # ---- create/seal (plasma lifecycle) --------------------------------
    def put(self, object_id: ObjectID, data, pin: bool = True) -> int:
        """Store a value.  For serialized payloads with a native backend
        this is SINGLE-COPY: a block is reserved in the shm segment
        (create), the flattened form is written straight into the
        mapping with NO store lock held (each payload byte moves exactly
        once, source buffer -> segment), then the entry is sealed and
        published.  Concurrent puts of different objects overlap their
        bulk copies; the lock only guards table bookkeeping."""
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        native_eligible = (self._native is not None
                           and isinstance(data, SerializedObject))
        with self._lock:
            done, result = self._existing_put_outcome_locked(object_id,
                                                             size)
            if done:
                return result
            if self._ensure_capacity(size):
                # The create request QUEUED (over-capacity, admitted
                # once seals/evictions/spills freed space): the lock was
                # released while waiting, so re-run the duplicate check.
                done, result = self._existing_put_outcome_locked(
                    object_id, size)
                if done:
                    return result
            reservation = None
            if native_eligible:
                reservation = self._reserve_native_locked(
                    object_id, data.flat_nbytes)
            e = _Entry(data=None if reservation is not None else data,
                       size=size)
            e.sealed = reservation is None
            e.primary = pin
            self._entries[object_id] = e
            self._used += size
            if reservation is None:
                self._lock.notify_all()
                return size
        # Bulk copy OUTSIDE the lock.
        self._fill_reservation(object_id, e, data, reservation)
        return size

    def _existing_put_outcome_locked(self, object_id: ObjectID,
                                     size: int):
        """Duplicate-put handling (must hold lock): returns
        ``(True, size_to_return)`` when the put should short-circuit on
        an existing entry, ``(False, 0)`` when the caller should store
        its own copy."""
        existing = self._entries.get(object_id)
        if existing is None:
            return False, 0
        if existing.sealed:
            return True, existing.size
        # Another putter is mid-copy: wait for its seal
        # (idempotent re-put, plasma create-in-progress reply).
        self._wait_sealed_locked(object_id)
        existing = self._entries.get(object_id)
        if existing is not None:
            # Sealed: idempotent success with the winner's size.
            # Still unsealed after the wait: stuck writer —
            # don't double-store under it.
            return True, existing.size if existing.sealed else size
        # Deleted mid-copy: the winner's bytes are gone — store OUR
        # copy (returning success with no stored value would surface
        # as a spurious ObjectLost).
        return False, 0

    def _fill_reservation(self, object_id: ObjectID, e: _Entry, data,
                          reservation) -> None:
        key = object_id.binary()
        nbytes, offset = reservation
        handle = None
        if offset == _ADOPT:
            # The key was already sealed in the segment (worker-written
            # return re-put): adopt it, no copy.
            handle = _NativeHandle(self._native, key, nbytes)
        else:
            try:
                data.write_into(self._native.view(offset, nbytes))
                self._native.seal(key)
                handle = _NativeHandle(self._native, key, nbytes)
                self.stats["native_put_bytes"] += nbytes
                self.stats["native_puts"] += 1
            except Exception:
                try:
                    self._native.delete(key)
                except Exception:
                    pass
        with self._lock:
            if self._entries.get(object_id) is not e:
                # Deleted while mid-copy: drop the orphaned native block.
                if handle is not None and offset != _ADOPT:
                    handle.delete()
                return
            e.data = handle if handle is not None else data
            e.sealed = True
            self._lock.notify_all()

    def _wait_sealed_locked(self, object_id: ObjectID,
                            timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            e = self._entries.get(object_id)
            if e is None or e.sealed:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._lock.wait(timeout=min(remaining, 0.5))

    def _reserve_native_locked(self, object_id: ObjectID, nbytes: int):
        """Reserve a segment block with the create-request retry flow
        (create_request_queue.h parity): ``try_create`` returns a
        RETRIABLE-OOM code (never throws) — on OOM, ask the native LRU
        for victims, spill them through the Python IO path, and retry;
        returns ``(nbytes, offset)``, ``(nbytes, _ADOPT)`` when the key
        is already sealed natively, or None (python-held buffers, the
        fallback allocation) only when the segment genuinely cannot fit
        the object.  Must hold the store lock."""
        key = object_id.binary()
        need = nbytes + 128
        for attempt in range(4):   # 3 escalations + final retry
            try:
                status, off = self._native.try_create(key, nbytes)
            except Exception:
                return None
            if status == _shm.CREATE_OK:
                return (nbytes, off)
            if status == _shm.CREATE_DUPLICATE:
                # Duplicate key: adopt if sealed, else give up.
                loc = self._native.locate(key)
                return (loc[1], _ADOPT) if loc is not None else None
            if status == _shm.CREATE_PENDING:
                # Deferred-free in progress (a client still holds the
                # old bytes pinned): the key is unusable until the last
                # release — python fallback.
                return None
            # CREATE_OOM — retriable.
            free = self._native.capacity - self._native.used_bytes()
            # Escalating eviction: first the byte shortfall, then a
            # full object's worth of LRU neighbours (total free can
            # exceed the request while no HOLE fits it), finally
            # everything evictable — coalescing then yields the
            # largest hole the pinned islands allow.
            if attempt == 0:
                shortfall = max(1, need - free)
            elif attempt == 1:
                shortfall = need
            else:
                shortfall = self._native.capacity
            victims = self._native.choose_victims(shortfall)
            if not victims:
                return None
            progressed = False
            for vkey in victims:
                voi = ObjectID(vkey)
                ve = self._entries.get(voi)
                if ve is not None and isinstance(ve.data, _NativeHandle):
                    # The native LRU only knows CLIENT pins: a python
                    # reader pin (spill-during-pin refused), an async
                    # spill in flight (spilling — finish_spill_batch
                    # would re-release the budget a second time), and
                    # an unsealed put must all refuse eviction here,
                    # same as the spill paths.  (No recency guard: OOM
                    # eviction must work on hot stores — plasma
                    # semantics — readers are protected by pins.)
                    if not self._spillable_locked(ve):
                        continue
                    try:
                        self._spill(voi, ve)     # reads + frees native
                        self.stats["evicted_objects"] += 1
                        progressed = True
                    except Exception:
                        # Victim couldn't spill (e.g. disk fault): skip
                        # it — other victims / the python fallback keep
                        # the put alive.
                        self.stats["spill_errors"] += 1
                else:
                    self._native.delete(vkey)
                    progressed = True
            if not progressed:
                # Every victim refused (pinned / mid-spill / recently
                # read): escalating the shortfall cannot help — fall to
                # the python path, which queues on the store condition.
                return None
        return None

    def reserve_native(self, object_id: ObjectID, nbytes: int):
        """Public reservation surface (worker-return shm_create): runs
        the same eviction-retry flow under the store lock; returns the
        block offset or None."""
        if self._native is None:
            return None
        with self._lock:
            r = self._reserve_native_locked(object_id, nbytes)
        if r is None or r[1] == _ADOPT:
            return None
        return r[1]

    def create_transfer_writer(self, object_id: ObjectID, nbytes: int,
                               pin: bool = False):
        """Writer for an incoming transfer (pull path): reserves a
        segment block the chunk pipeline assembles into directly, and on
        seal registers the entry + wakes waiters — no intermediate
        ``bytearray``.  Falls back to a heap buffer when no native
        backend is attached or the segment cannot fit the object.

        The store budget is enforced HERE (spilling as needed, raising
        ObjectStoreFullError when even spilling cannot make room) and
        the bytes stay charged to ``_transfer_reserved`` until
        seal/abort, so N concurrent pulls cannot collectively
        over-commit what a single put could not.

        Single-writer guarantee: if another transfer writer for this
        object is already in flight, this call BLOCKS until it
        seals/aborts, then returns None when the object landed (the
        caller's pull goal is met without streaming a duplicate copy —
        and, crucially, without a second writer whose abort/seal could
        free the winner's native block underneath its sealed entry).
        """
        with self._lock:
            while object_id in self._active_transfers:
                self._lock.wait(0.5)
            e = self._entries.get(object_id)
            if e is not None and e.sealed:
                return None          # the racing transfer delivered it
            # Claim BEFORE the capacity wait: _ensure_capacity can
            # release the lock (create-queue backpressure), and an
            # unclaimed window there would admit a second writer —
            # the very race this claim exists to close.
            self._active_transfers.add(object_id)
            r = None
            try:
                self._ensure_capacity(nbytes)
                e = self._entries.get(object_id)
                if e is not None and e.sealed:
                    # A plain put landed the object while we waited
                    # for capacity: adopt it.
                    self._active_transfers.discard(object_id)
                    self._lock.notify_all()
                    return None
                self._transfer_reserved += nbytes
                try:
                    if self._native is not None:
                        r = self._reserve_native_locked(object_id,
                                                        nbytes)
                except BaseException:
                    self._transfer_reserved -= nbytes
                    raise
            except BaseException:
                self._active_transfers.discard(object_id)
                self._lock.notify_all()
                raise
        if r is not None and r[1] != _ADOPT:
            return _SegmentTransferWriter(self, object_id, nbytes,
                                          r[1], pin)
        return _HeapTransferWriter(self, object_id, nbytes, pin)

    def _release_transfer_reservation(self, nbytes: int,
                                      object_id: Optional[ObjectID] = None
                                      ) -> None:
        with self._lock:
            self._transfer_reserved -= nbytes
            if object_id is not None:
                self._active_transfers.discard(object_id)
                self._lock.notify_all()

    def register_native_entry(self, object_id: ObjectID, size: int):
        """Adopt an object a CLIENT created+sealed directly in the
        native segment (worker-written return): table entry wrapping
        the native handle, a primary copy.  Admitted UNCONDITIONALLY:
        the bytes already physically occupy the segment (the client's
        create reserved them), so blocking or failing here would lose a
        sealed return — over-threshold pressure is handed to the async
        spiller instead."""
        with self._lock:
            if object_id in self._entries:
                return
            e = _Entry(data=_NativeHandle(self._native,
                                          object_id.binary(), size),
                       size=size)
            e.primary = True
            self._entries[object_id] = e
            self._used += size
            if self._spill_manager is not None and \
                    self._used + self._transfer_reserved > \
                    int(self.capacity * self.spill_threshold):
                self._spill_manager.request_spill()
            self._lock.notify_all()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                # Unsealed = a put's bulk copy is still in flight; the
                # bytes are not readable yet (plasma: Get sees sealed
                # objects only).
                return None
            e.last_access = time.monotonic()
            if e.data is None and e.spilled_path is not None:
                self._restore(object_id, e)
            return e

    def get_serialized(self, object_id: ObjectID) -> Optional[SerializedObject]:
        e = self.get(object_id)
        if e is None:
            return None
        data = e.data
        if isinstance(data, _NativeHandle):
            blob = data.read()
            if blob is None:        # backing vanished under the entry
                self.drop_vanished(object_id)
                return None
            return SerializedObject.from_bytes(blob)
        if isinstance(data, DeviceObject):
            return data.to_serialized()
        return data

    def pin(self, object_id: ObjectID):
        """Store-level pin: protects from Python-side spill selection.
        Native pins are CLIENT pins only (shm surface) — they defer the
        native free while a worker reads through its mapping."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pin_count += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pin_count > 0:
                e.pin_count -= 1

    def drop_vanished(self, object_id: ObjectID) -> bool:
        """Self-heal a poisoned entry: a SEALED native-handle entry
        whose native key no longer exists (every seal path had the key
        sealed natively at registration, so ``locate`` returning None
        means the block was deleted underneath — a lost race some free
        path won).  The entry is unrecoverable local state, and worse,
        it makes ``contains`` lie: pulls short-circuit "local" forever
        while reads miss forever.  Drop it so the pull path can
        re-fetch from a genuine location.  Returns True if dropped."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or \
                    not isinstance(e.data, _NativeHandle):
                return False
            if self._native is not None and \
                    self._native.locate(e.data.key) is not None:
                return False        # readable after all; nothing to heal
            del self._entries[object_id]
            self._used -= e.size
            self.stats["vanished_objects"] = \
                self.stats.get("vanished_objects", 0) + 1
            if e.spilled_path:
                self._release_spill_region_locked(object_id,
                                                  e.spilled_path)
            self._lock.notify_all()
        return True

    def delete(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            # An entry holds store budget while its bytes are in memory
            # (data set) or reserved by an in-flight put (unsealed
            # placeholder); spilled entries released theirs at spill.
            if e.data is not None or (not e.sealed
                                      and e.spilled_path is None):
                self._used -= e.size
            if isinstance(e.data, _NativeHandle):
                # Client (worker-held) pins defer the actual free.
                e.data.delete()
            if e.spilled_path:
                self._release_spill_region_locked(object_id,
                                                  e.spilled_path)
            # Freed budget may admit a queued create request.
            self._lock.notify_all()

    def _release_spill_region_locked(self, object_id: ObjectID,
                                     url: str) -> None:
        """Drop ``object_id``'s claim on its spill file; fused batch
        files are unlinked only when their LAST live object goes."""
        path, _, _ = _parse_spill_url(url)
        live = self._spill_files.get(path)
        if live is not None:
            live.discard(object_id)
            if live:
                return
            del self._spill_files[path]
        try:
            os.unlink(path)
        except OSError:
            pass

    # ---- capacity / spilling -------------------------------------------
    def _spillable_locked(self, e: _Entry) -> bool:
        """Spill candidate: sealed bytes in memory, no READER pins (an
        executor or shm client is mid-read — spill-during-pin refused),
        not device-resident, not already being spilled by the async
        manager."""
        return (e.data is not None and e.sealed and not e.is_device
                and e.pin_count == 0 and not e.spilling)

    def _spill_safe_locked(self, e: _Entry, now: float) -> bool:
        """Spillable AND not touched within the last second.  ``get()``
        returns the entry and callers read ``e.data`` WITHOUT a pin, so
        a spill that nulls the payload right after an access races that
        unpinned read (deserialize(None) on a healthy object).  Recency
        is the guard every background/eviction path shares; only the
        explicit test hook ``spill_now`` skips it."""
        return self._spillable_locked(e) and now - e.last_access > 1.0

    def _spill_toward_locked(self, target: int, incoming: int) -> None:
        """Inline LRU spill until ``used + reserved + incoming`` fits
        under ``target`` or candidates run out.  Per-victim failures
        (disk faults) skip the victim rather than failing the caller."""
        now = time.monotonic()
        candidates = sorted(
            ((e.last_access, oid) for oid, e in self._entries.items()
             if self._spill_safe_locked(e, now)),
            key=lambda t: t[0])
        for _, oid in candidates:
            if self._used + self._transfer_reserved + incoming <= target:
                return
            e = self._entries.get(oid)
            if e is None or not self._spillable_locked(e):
                continue
            try:
                self._spill(oid, e)
            except Exception:
                self.stats["spill_errors"] += 1

    def _ensure_capacity(self, incoming: int, wait: bool = True) -> bool:
        """Admit a reservation of ``incoming`` bytes (must hold lock).

        Fast path: fits under the spill threshold — admit.  Pressure
        path: inline-spill LRU entries toward the threshold.  Full
        path (plasma ``create_request_queue`` semantics): the request
        QUEUES on the store condition — releasing the lock — and is
        retried as deletes/evictions/spills free space, surfacing
        ObjectStoreFullError only after the configured grace deadline.
        Returns True when the request waited (callers must re-validate
        any state read before the call)."""
        limit = int(self.capacity * self.spill_threshold)
        if self._used + self._transfer_reserved + incoming <= limit:
            return False
        if self._spill_manager is None:
            # Bare store (no io thread): spill inline toward the
            # threshold on the caller's thread.
            self._spill_toward_locked(limit, incoming)
        if self._used + self._transfer_reserved + incoming <= \
                self.capacity:
            # Over threshold but under hard capacity: admit, and let
            # the async spiller work the utilization back down off the
            # put path (fused batches on its io thread — inline
            # spilling here would serialize one-file-per-object writes
            # into every over-threshold put).
            if self._spill_manager is not None:
                self._spill_manager.request_spill()
            return False
        if incoming > self.capacity:
            raise self._full_error(incoming, infeasible=True)
        if not wait:
            raise self._full_error(incoming)
        cfg = get_config()
        deadline = time.monotonic() + cfg.object_store_full_grace_period_s
        retry_s = max(cfg.object_store_full_retry_ms, 1) / 1000.0
        self._create_waiters += 1
        self.stats["queued_creates"] += 1
        flight_recorder.record(
            "store.create_queued", bytes=incoming,
            used=self._used, reserved=self._transfer_reserved,
            capacity=self.capacity, waiters=self._create_waiters)
        t0 = time.monotonic()
        try:
            while self._used + self._transfer_reserved + incoming > \
                    self.capacity:
                if self._spill_manager is not None:
                    # The io thread frees space off this thread; its
                    # finish_spill_batch notify wakes us.  Inline
                    # spilling here would run per-object disk writes
                    # UNDER the store lock on every retry, stalling
                    # every concurrent get/put behind file IO.
                    self._spill_manager.request_spill()
                else:
                    # Bare store: entries sealed while we waited are
                    # fresh candidates.
                    self._spill_toward_locked(limit, incoming)
                if self._used + self._transfer_reserved + incoming <= \
                        self.capacity:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.stats["create_queue_timeouts"] += 1
                    raise self._full_error(incoming, queued=True)
                self._lock.wait(timeout=min(remaining, retry_s))
        finally:
            self._create_waiters -= 1
            self.stats["create_queue_wait_ms"] += \
                (time.monotonic() - t0) * 1000.0
        return True

    def _full_error(self, incoming: int, infeasible: bool = False,
                    queued: bool = False) -> exceptions.ObjectStoreFullError:
        """Actionable OOM context: capacity vs request, in-flight
        reservations, what is evictable, queue depth, segment holes."""
        nid = getattr(self.node_id, "hex",
                      lambda: str(self.node_id))()[:12]
        evictable = sum(e.size for e in self._entries.values()
                        if self._spillable_locked(e))
        msg = (f"cannot reserve {incoming} bytes on node {nid}: "
               f"{self._used}/{self.capacity} bytes used, "
               f"{self._transfer_reserved} reserved by in-flight "
               f"transfers, {evictable} evictable, "
               f"{self._create_waiters} queued create(s)")
        if self._native is not None:
            try:
                msg += (f"; native segment "
                        f"{self._native.used_bytes()}"
                        f"/{self._native.capacity} used, largest free "
                        f"block {self._native.largest_free_block()}")
            except Exception:
                pass
        if infeasible:
            msg += ("; the object exceeds total store capacity and can "
                    "NEVER fit — raise object_store_memory")
        elif queued:
            grace = get_config().object_store_full_grace_period_s
            msg += (f"; queued {grace}s (object_store_full_grace_period"
                    f"_s) without space freeing — raise "
                    f"object_store_memory, lower "
                    f"object_spilling_threshold, or check spill_dir "
                    f"{self.spill_dir}")
        err = exceptions.ObjectStoreFullError(msg)
        # Callers that retry/queue on store-full (pulls, puts) must NOT
        # retry the infeasible variant: the object can never fit, so
        # retrying just converts the actionable message into a generic
        # timeout after the full grace/pull deadline.
        err.infeasible = bool(infeasible)
        return err

    def _spill(self, object_id: ObjectID, e: _Entry):
        """Synchronous single-object spill (eviction path; must hold
        lock).  Re-spilling a restored entry is FREE: the on-disk bytes
        are immutable, so the budget is released without rewriting."""
        if e.spilled_path is not None:
            if e.data is not None:
                if isinstance(e.data, _NativeHandle):
                    e.data.delete()
                e.data = None
                self._used -= e.size
                self.stats["spilled_objects"] += 1
                self._lock.notify_all()
            return
        data = e.data
        path = os.path.join(self.spill_dir, object_id.hex())
        fault_injection.hook("spill.write")
        if isinstance(data, _NativeHandle):
            # Stream the segment view straight to disk, THEN free: the
            # view is invalid once the allocator reuses the block.  (A
            # client-pinned object's native free defers to its last
            # release; the spill copy is taken regardless.)
            view = data.read()
            if view is None:
                # Backing vanished under the sealed entry (the lost
                # free race the read paths heal): nothing to spill.
                raise ObjectVanishedError(
                    f"native copy of {object_id} vanished before spill")
            nbytes = view.nbytes
            with open(path, "wb") as f:
                f.write(view)
            del view
            data.delete()
        else:
            if isinstance(data, DeviceObject):
                data = data.to_serialized()
            nbytes = data.flat_nbytes
            with open(path, "wb") as f:
                f.write(data.to_bytes())
        self._register_spill_locked(object_id, e, path, 0, nbytes)

    def _register_spill_locked(self, object_id: ObjectID, e: _Entry,
                               path: str, offset: int,
                               nbytes: int) -> None:
        """Publish a completed spill: record the URL, release the
        budget, wake queued creates, and report the spilled_url to the
        owner (reference_counter)."""
        url = _spill_url(path, offset, nbytes)
        e.spilled_path = url
        e.data = None
        e.spilling = False
        self._used -= e.size
        self._spill_files.setdefault(path, set()).add(object_id)
        self.stats["spilled_bytes"] += nbytes
        self.stats["spilled_objects"] += 1
        self._lock.notify_all()
        if self._on_spilled is not None:
            try:
                self._on_spilled(object_id, url)
            except Exception:
                pass

    def _restore(self, object_id: ObjectID, e: _Entry):
        from ray_tpu.util import tracing
        path, offset, size = _parse_spill_url(e.spilled_path)
        fault_injection.hook("restore.read")
        from ray_tpu._private.config import get_config as _get_config
        from ray_tpu._private import worker_context
        _ctx = worker_context.current_task_spec()
        with tracing.span("object.restore", category="spill",
                          bytes=size, object_id=object_id.hex(),
                          task_id=(_ctx.task_id.hex()
                                   if _ctx is not None else ""),
                          force=_get_config().job_profiler_enabled), \
                open(path, "rb") as f:
            f.seek(offset)
            blob = f.read(size)
        e.data = SerializedObject.from_bytes(blob)
        self._used += e.size
        self.stats["restored_bytes"] += len(blob)
        self.stats["restored_objects"] += 1
        flight_recorder.record("spill.restore",
                               obj=object_id.hex()[:12], bytes=size)
        # Restores re-charge the budget without a capacity gate (a get
        # must not deadlock on its own store): hand the overshoot to
        # the async spiller so a restore-heavy read phase cannot pin
        # utilization above the threshold indefinitely.
        if self._spill_manager is not None and \
                self._used + self._transfer_reserved > \
                int(self.capacity * self.spill_threshold):
            self._spill_manager.request_spill()

    # ---- async-spill batch surface (LocalObjectManager) ----------------
    def select_spill_victims(self, max_bytes: int):
        """Pick LRU spill candidates totalling up to ``max_bytes``
        (at least one if any exists), mark them ``spilling`` and pin
        their native blocks so the copy-out can run OUTSIDE the store
        lock.  Returns ``[(object_id, entry, source)]`` where source is
        a pinned segment view or a SerializedObject."""
        out = []
        with self._lock:
            now = time.monotonic()
            candidates = sorted(
                ((e.last_access, oid) for oid, e in self._entries.items()
                 if self._spill_safe_locked(e, now)
                 and e.spilled_path is None),
                key=lambda t: t[0])
            total = 0
            for _, oid in candidates:
                if out and total >= max_bytes:
                    break
                e = self._entries[oid]
                source = e.data
                if isinstance(source, _NativeHandle):
                    if not self._native.pin(source.key):
                        continue     # freed in the window
                    view = source.read()
                    if view is None:
                        self._native.unpin(source.key)
                        continue
                    source = view
                elif isinstance(source, DeviceObject):
                    continue
                e.spilling = True
                total += e.size
                out.append((oid, e, source))
            # Restored-then-unpinned entries re-spill for free (bytes
            # already on disk): fold them in — the shared recency guard
            # keeps an eager re-spill from nulling the payload out from
            # under an unpinned reader (restore -> respill -> failed
            # pull loop under sustained pressure).  Recently-read
            # entries just wait for the next sweep.
            for oid, e in list(self._entries.items()):
                if (e.spilled_path is not None and e.data is not None
                        and self._spill_safe_locked(e, now)):
                    self._spill(oid, e)
        return out

    def finish_spill_batch(self, path: str, results) -> int:
        """Finalize an async batch: ``results`` is
        ``[(object_id, entry, offset, nbytes, ok)]``.  Entries deleted
        mid-copy are skipped (delete won; their file region is dead
        weight until the file's last object goes).  Returns the number
        of entries actually transitioned to spilled."""
        done = 0
        with self._lock:
            for object_id, e, offset, nbytes, ok in results:
                if isinstance(e.data, _NativeHandle):
                    self._native.unpin(e.data.key)
                current = self._entries.get(object_id)
                if current is not e:
                    e.spilling = False   # deleted mid-spill: delete won
                    continue
                if not ok:
                    e.spilling = False
                    self.stats["spill_errors"] += 1
                    continue
                if isinstance(e.data, _NativeHandle):
                    e.data.delete()      # free the segment block
                self._register_spill_locked(object_id, e, path, offset,
                                            nbytes)
                done += 1
            self._lock.notify_all()
        return done

    def over_spill_threshold(self) -> bool:
        with self._lock:
            return self._used + self._transfer_reserved > \
                int(self.capacity * self.spill_threshold)

    def spill_shortfall(self) -> int:
        """Bytes over the spill threshold (<= 0 when under it)."""
        with self._lock:
            return (self._used + self._transfer_reserved
                    - int(self.capacity * self.spill_threshold))

    def open_spilled_view(self, object_id: ObjectID):
        """Zero-restore read surface over a spilled object: an mmap'd
        view of its spill-file region, so a chunked transfer can be
        served straight from disk without pulling the bytes back into
        the store budget.  Returns ``(memoryview, release)`` or None."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or e.spilled_path is None \
                    or e.data is not None:
                return None
            url = e.spilled_path
        path, offset, size = _parse_spill_url(url)
        import mmap as mmap_mod
        try:
            f = open(path, "rb")
        except OSError:
            return None              # deleted in the window
        try:
            mm = mmap_mod.mmap(f.fileno(), 0, prot=mmap_mod.PROT_READ)
        except (OSError, ValueError):
            f.close()
            return None
        f.close()                    # mmap holds the file alive
        view = memoryview(mm)[offset:offset + size]

        def release(mm=mm, view=view):
            try:
                view.release()
                mm.close()
            except Exception:
                pass

        return view, release

    # ---- chunk relay (collective broadcast) -----------------------------
    def _register_partial(self, object_id: ObjectID, nbytes: int,
                          read_raw) -> "_PartialTransfer":
        """Publish an in-flight transfer as relayable (called by the
        writer holding the single-writer claim, so no double
        registration is possible)."""
        p = _PartialTransfer(self, object_id, nbytes, read_raw)
        with self._lock:
            self._partials[object_id] = p
        return p

    def _unregister_partial(self, object_id: ObjectID,
                            p: "_PartialTransfer") -> None:
        with self._lock:
            if self._partials.get(object_id) is p:
                del self._partials[object_id]

    def open_relay_source(self, object_id: ObjectID
                          ) -> Optional["_PartialTransfer"]:
        """Relay read surface over an in-flight transfer of
        ``object_id``, or None when nothing is being assembled here —
        the sender half of chunk-level relay.  The returned object
        stays valid past seal/abort (reads then resolve through the
        sealed entry / fail cleanly)."""
        with self._lock:
            return self._partials.get(object_id)

    def num_partials(self) -> int:
        with self._lock:
            return len(self._partials)

    def read_sealed_range(self, object_id: ObjectID, start: int,
                          end: int) -> Optional[bytes]:
        """Byte range of a SEALED object (relay tail reads after the
        upstream transfer sealed): spilled objects are served from
        their spill-file mmap, native blocks under a pin — None when
        the object is gone (the downstream puller re-selects)."""
        spilled = self.open_spilled_view(object_id)
        if spilled is not None:
            view, release = spilled
            try:
                return bytes(view[start:end])
            finally:
                release()
        e = self.get(object_id)
        if e is None:
            return None
        data = e.data
        if isinstance(data, _NativeHandle) and self._native is not None:
            key = data.key
            if self._native.pin(key):
                try:
                    view = data.read()
                    if view is not None:
                        return bytes(view[start:end])
                finally:
                    self._native.unpin(key)
        # No O(chunk) read surface (python-held winner / vanished
        # block): None — the relay caller materializes ONCE and caches,
        # never per chunk.
        return None

    def spill_now(self) -> int:
        """Force-spill all spillable entries (test/chaos hook).
        Reader-pinned entries are refused, same as the background
        path."""
        n = 0
        with self._lock:
            for oid, e in list(self._entries.items()):
                if self._spillable_locked(e):
                    try:
                        self._spill(oid, e)
                    except Exception:
                        self.stats["spill_errors"] += 1
                        continue
                    n += 1
        return n

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)


class InPlasmaMarker:
    """Memory-store marker: the value's bytes live in a node store.

    Sealed into the owner's memory store when a large return value lands in
    a node store, so owner-side waits unblock promptly (the reference's
    "in plasma" error-code reply on the Get path).
    """

    __slots__ = ("node_id", "total_bytes")

    def __init__(self, node_id):
        self.node_id = node_id
        self.total_bytes = 0


#: Reservation sentinel: the key is already sealed in the segment —
#: adopt the existing block instead of copying.
_ADOPT = -1


class _NativeHandle:
    """Handle to an object held by the native C++ shm store."""

    __slots__ = ("store", "key", "nbytes")

    def __init__(self, store, key: bytes, nbytes: int):
        self.store = store
        self.key = key
        self.nbytes = nbytes

    def read(self) -> bytes:
        return self.store.get(self.key)

    def delete(self):
        try:
            self.store.delete(self.key)
        except Exception:
            pass


def _maybe_register_partial(store: "NodeObjectStore",
                            object_id: ObjectID, nbytes: int,
                            read_raw) -> Optional["_PartialTransfer"]:
    """Writer-side relay registration gate: multi-chunk transfers only
    (single-chunk objects gain nothing from a relay hop), and only when
    relay is enabled — the bench's naive arm must stay honestly
    relay-free."""
    cfg = get_config()
    if not cfg.object_transfer_relay_enabled or \
            nbytes <= cfg.object_manager_chunk_size:
        return None
    return store._register_partial(object_id, nbytes, read_raw)


class _SegmentTransferWriter:
    """Incoming-transfer sink over a reserved shm block: the chunk
    pipeline writes each arriving chunk straight into the segment at
    its final offset (ObjectBufferPool chunk assembly without the
    intermediate ``bytearray``); ``seal`` publishes the entry.  While
    in flight the assembled prefix is relayable to downstream pullers
    through the store's partial registry."""

    __slots__ = ("_store", "_object_id", "nbytes", "_offset", "_pin",
                 "_view", "_reserved", "_partial")

    def __init__(self, store: "NodeObjectStore", object_id: ObjectID,
                 nbytes: int, offset: int, pin: bool):
        self._store = store
        self._object_id = object_id
        self.nbytes = nbytes
        self._offset = offset
        self._pin = pin
        view = store._native.view(offset, nbytes)
        self._view = view
        self._reserved = True
        # The relay raw-read closes over its OWN reference to the view:
        # seal/abort null the writer's attribute, but readers are
        # drained before the backing block can be recycled.
        self._partial = _maybe_register_partial(
            store, object_id, nbytes, lambda s, e: view[s:e])

    def write(self, offset: int, data) -> None:
        from ray_tpu._private.serialization import copy_into_view
        copy_into_view(self._view, offset, data)
        if self._partial is not None:
            self._partial.advance(
                offset, getattr(data, "nbytes", None) or len(data))

    def seal(self) -> None:
        store = self._store
        key = self._object_id.binary()
        partial = self._partial
        if partial is not None:
            # Raw relay reads must drain BEFORE the block becomes a
            # sealed entry eviction could recycle; reads arriving in
            # the window retry into the sealed path below.
            partial.quiesce_for_seal()
        self._view = None
        try:
            store._native.seal(key)
        except BaseException:
            # A failed native seal must still release the reservation
            # AND the single-writer claim (a leaked claim hangs every
            # future pull of this object forever) and drop the block.
            if partial is not None:
                store._unregister_partial(self._object_id, partial)
                partial.mark_failed()
            with store._lock:
                if self._reserved:
                    self._reserved = False
                    store._transfer_reserved -= self.nbytes
                try:
                    store._native.delete(key)
                except Exception:
                    pass
                store._active_transfers.discard(self._object_id)
                store._lock.notify_all()
            raise
        try:
            with store._lock:
                if self._reserved:
                    self._reserved = False
                    store._transfer_reserved -= self.nbytes
                store._active_transfers.discard(self._object_id)
                existing = store._entries.get(self._object_id)
                if existing is not None:
                    # Lost a materialization race; keep the winner
                    # unless it is (now) backed by this very block.
                    if not (isinstance(existing.data, _NativeHandle)
                            and existing.data.key == key):
                        store._native.delete(key)
                    store._lock.notify_all()
                    return
                e = _Entry(data=_NativeHandle(store._native, key,
                                              self.nbytes),
                           size=self.nbytes)
                e.primary = self._pin
                store._entries[self._object_id] = e
                store._used += self.nbytes
                store._lock.notify_all()
        finally:
            # Promote AFTER the entry is registered: relay readers that
            # observe ``sealed`` resolve through the store entry (the
            # lost-race arm registered the winner's — same bytes).
            if partial is not None:
                store._unregister_partial(self._object_id, partial)
                partial.mark_sealed()

    def abort(self) -> None:
        store = self._store
        partial = self._partial
        if partial is not None:
            # Fail downstream relay readers FIRST and drain raw reads:
            # the native delete below recycles the block they would
            # otherwise still be copying from.
            store._unregister_partial(self._object_id, partial)
            partial.mark_failed()
        self._view = None
        # ONE lock acquisition for reservation release, native delete
        # AND the single-writer claim release: dropping the claim first
        # would wake a waiting successor whose freshly-reserved block
        # (same key) this delete would then free underneath it.
        with store._lock:
            if self._reserved:
                self._reserved = False
                store._transfer_reserved -= self.nbytes
            try:
                # Never free the native key underneath a SEALED entry
                # another path registered (put / racing seal): that is
                # exactly the lost-free race behind vanished_objects.
                existing = store._entries.get(self._object_id)
                if not (existing is not None and existing.sealed and
                        isinstance(existing.data, _NativeHandle) and
                        existing.data.key == self._object_id.binary()):
                    store._native.delete(self._object_id.binary())
            except Exception:
                pass
            store._active_transfers.discard(self._object_id)
            store._lock.notify_all()


class _HeapTransferWriter:
    """Fallback transfer sink when no native segment is available (or
    the object exceeds it): assembles on the heap, seals via a normal
    store put.  The heap buffer is just as relayable as a segment block
    — the partial raw-read closes over the bytearray itself, so it
    stays valid for late relay reads even after seal hands the bytes to
    the store."""

    __slots__ = ("_store", "_object_id", "nbytes", "_pin", "_buf",
                 "_reserved", "_partial")

    def __init__(self, store: "NodeObjectStore", object_id: ObjectID,
                 nbytes: int, pin: bool):
        self._store = store
        self._object_id = object_id
        self.nbytes = nbytes
        self._pin = pin
        buf = bytearray(nbytes)
        self._buf = buf
        self._reserved = True
        self._partial = _maybe_register_partial(
            store, object_id, nbytes,
            lambda s, e: memoryview(buf)[s:e])

    def write(self, offset: int, data) -> None:
        self._buf[offset:offset + len(data)] = data
        if self._partial is not None:
            self._partial.advance(
                offset, getattr(data, "nbytes", None) or len(data))

    def _release(self) -> None:
        if self._reserved:
            self._reserved = False
            self._store._release_transfer_reservation(self.nbytes,
                                                      self._object_id)

    def seal(self) -> None:
        store = self._store
        partial = self._partial
        if partial is not None:
            partial.quiesce_for_seal()
        sealed_ok = False
        try:
            # from_bytes INSIDE the try: a corrupt payload must not
            # leak the reservation or the single-writer claim (a
            # leaked claim hangs every future pull of this object).
            restored = SerializedObject.from_bytes(bytes(self._buf))
            self._buf = None
            if self._reserved:
                self._reserved = False
                # put() re-charges _used itself; the single-writer
                # claim is held until the entry is registered so a
                # waiting duplicate pull adopts it instead of starting
                # a second transfer.
                store._release_transfer_reservation(self.nbytes)
            store.put(self._object_id, restored, pin=self._pin)
            sealed_ok = True
        finally:
            self._buf = None
            with store._lock:
                if self._reserved:
                    self._reserved = False
                    store._transfer_reserved -= self.nbytes
                store._active_transfers.discard(self._object_id)
                store._lock.notify_all()
            if partial is not None:
                store._unregister_partial(self._object_id, partial)
                if sealed_ok:
                    # The bytearray lives on in the read closure: tail
                    # relay reads stay O(chunk), not a full
                    # re-materialization per chunk via the store.
                    partial.mark_sealed(raw_still_valid=True)
                else:
                    partial.mark_failed()

    def abort(self) -> None:
        partial = self._partial
        if partial is not None:
            self._store._unregister_partial(self._object_id, partial)
            partial.mark_failed()
        self._buf = None
        self._release()


def segment_chunk_source(store: "NodeObjectStore"):
    """``get_source`` hook for :class:`ray_tpu.rpc.chunked.ChunkServer`:
    serve outgoing transfers straight from the store's shm segment under
    a native pin (released when the session closes), so the SENDER never
    flattens the object either.  SPILLED objects are served straight
    from their spill-file region over an mmap — a remote pull never
    forces a full in-memory restore on the sender."""

    def get_source(oid_bin: bytes):
        if store is None:
            return None
        spilled = store.open_spilled_view(ObjectID(oid_bin))
        if spilled is not None:
            return spilled
        native = store._native
        if native is None:
            return None
        entry = store.get(ObjectID(oid_bin))
        if entry is None or not isinstance(entry.data, _NativeHandle):
            return None
        key = entry.data.key
        if not native.pin(key):
            return None              # freed in the window
        view = native.get(key)
        if view is None:
            native.unpin(key)
            return None
        return view, lambda: native.unpin(key)

    return get_source


class ObjectVanishedError(LookupError):
    """The entry's backing bytes were deleted between the store lookup
    and the read (a concurrent free — e.g. the owner died and the
    refcount cascade dropped the copy).  Callers treat it as a store
    miss and re-resolve; the owner-death / reconstruction machinery
    decides what the miss means."""


def entry_value(entry: _Entry):
    """Deserialize an entry to its Python value (raising stored errors)."""
    if entry.error is not None:
        raise entry.error
    data = entry.data
    if isinstance(data, DeviceObject):
        return data.value
    if isinstance(data, _NativeHandle):
        blob = data.read()
        if blob is None:
            raise ObjectVanishedError(
                f"native copy of {entry!r} deleted mid-read")
        return deserialize(SerializedObject.from_bytes(blob))
    return deserialize(data)
