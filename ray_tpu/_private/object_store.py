"""Per-process and per-node object stores.

Parity targets:
  * ``CoreWorkerMemoryStore`` (reference
    ``src/ray/core_worker/store_provider/memory_store/``) — in-process store
    for small objects and pending futures; blocking ``Get`` with timeout.
  * Plasma (reference ``src/ray/object_manager/plasma/`` — shared-memory store
    with capacity accounting, pinning, LRU eviction and spill-to-disk via
    ``raylet/local_object_manager.cc``).  Here :class:`NodeObjectStore` is the
    plasma equivalent: host-memory slab per node, optional native C++
    shared-memory backend (``ray_tpu/native``), spill/restore to the session
    dir, and a **device-object extension** the reference never had — jax
    device buffers can live in the store without a host copy and are only
    materialized to host when crossing nodes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject, deserialize


class DeviceObject:
    """A store entry whose payload is a jax device array (or pytree).

    Zero-copy handoff: actors on the same node exchange the device buffer
    directly; a host copy happens only on spill or cross-node transfer.
    This is the TPU-native extension of plasma (SURVEY.md §7 "hard parts").
    """

    __slots__ = ("value", "nbytes")

    def __init__(self, value):
        import jax
        self.value = value
        self.nbytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(value)
            if hasattr(x, "dtype"))

    def to_serialized(self) -> SerializedObject:
        from ray_tpu._private.serialization import serialize
        return serialize(self.value)


class _Entry:
    __slots__ = ("data", "error", "size", "pin_count", "last_access",
                 "spilled_path", "sealed", "is_device")

    def __init__(self, data=None, error=None, size=0):
        self.data = data              # SerializedObject | DeviceObject | None
        self.error = error            # Exception to raise at get()
        self.size = size
        self.pin_count = 0
        self.last_access = time.monotonic()
        self.spilled_path: Optional[str] = None
        self.sealed = data is not None or error is not None
        self.is_device = isinstance(data, DeviceObject)


class MemoryStore:
    """In-process store: small objects, error markers, pending futures.

    ``get`` blocks on a condition variable until the object is sealed
    (reference: memory store ``GetAsync``/``Get`` with timeout).
    """

    def __init__(self):
        self._lock = threading.Condition()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._get_callbacks: Dict[ObjectID, list] = {}

    def put(self, object_id: ObjectID, data, error=None) -> int:
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.sealed:
                return entry.size  # idempotent re-put
            entry = _Entry(data=data, error=error, size=size)
            self._entries[object_id] = entry
            callbacks = self._get_callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(entry)
        return size

    def put_error(self, object_id: ObjectID, error: BaseException):
        self.put(object_id, None, error=error)

    def fail(self, object_id: ObjectID, error: BaseException):
        """Force-seal ``error`` over the entry, REPLACING any existing
        value (owner-death semantics: the owner's table was
        authoritative, so its loss invalidates the object even when
        bytes still exist somewhere — borrowers must observe the error,
        reference: OWNER_DIED reply on Get)."""
        with self._lock:
            entry = _Entry(data=None, error=error)
            self._entries[object_id] = entry
            callbacks = self._get_callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(entry)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> _Entry:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                e = self._entries.get(object_id)
                if e is not None and e.sealed:
                    e.last_access = time.monotonic()
                    return e
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise exceptions.GetTimeoutError(
                        f"Get timed out for {object_id}")
                self._lock.wait(timeout=remaining if remaining is None
                                else min(remaining, 0.5))

    def get_async(self, object_id: ObjectID, cb: Callable[[_Entry], None]):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed:
                pass
            else:
                self._get_callbacks.setdefault(object_id, []).append(cb)
                return
        cb(e)

    def cancel_get_async(self, object_id: ObjectID,
                         cb: Callable[[_Entry], None]):
        """Deregister a pending get_async callback (no-op if it already
        fired) — callers that stop waiting must not leak closures."""
        with self._lock:
            cbs = self._get_callbacks.get(object_id)
            if cbs is None:
                return
            try:
                cbs.remove(cb)
            except ValueError:
                return
            if not cbs:
                del self._get_callbacks[object_id]

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._entries.pop(object_id, None)
            self._get_callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


class NodeObjectStore:
    """Plasma-equivalent per-node store with capacity, pinning and spilling.

    Reference behaviors kept: create/seal lifecycle, primary-copy pinning
    (``local_object_manager.h:37``), spill-over-threshold with batched
    writes, restore-on-demand, delete-when-out-of-scope, fallback allocation
    never fails hard (OOM raises only if spilling cannot reclaim).
    """

    def __init__(self, node_id, capacity_bytes: int, spill_dir: str,
                 spill_threshold: float = 0.8, native_backend=None):
        self.node_id = node_id
        self.capacity = capacity_bytes
        self.spill_threshold = spill_threshold
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Condition()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._used = 0
        # Bytes reserved by in-flight transfer writers (charged before
        # the chunks land so concurrent pulls cannot over-commit the
        # budget; moved into _used at seal, dropped at abort).
        self._transfer_reserved = 0
        self._native = native_backend  # ray_tpu.native shm store, optional
        self.stats = {"spilled_bytes": 0, "restored_bytes": 0,
                      "spilled_objects": 0, "restored_objects": 0,
                      "evicted_objects": 0, "native_put_bytes": 0,
                      "native_puts": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        nid = getattr(node_id, "hex", lambda: str(node_id))()[:12]

        def _collect(store):
            labels = {"node": nid}
            record_internal("ray_tpu.object_store.used_bytes",
                            store._used, **labels)
            record_internal("ray_tpu.object_store.capacity_bytes",
                            store.capacity, **labels)
            record_internal("ray_tpu.object_store.num_objects",
                            len(store._entries), **labels)
            for k, v in store.stats.items():
                record_internal(f"ray_tpu.object_store.{k}", v, **labels)
        get_metrics_registry().register_collector(self, _collect)

    # ---- create/seal (plasma lifecycle) --------------------------------
    def put(self, object_id: ObjectID, data, pin: bool = True) -> int:
        """Store a value.  For serialized payloads with a native backend
        this is SINGLE-COPY: a block is reserved in the shm segment
        (create), the flattened form is written straight into the
        mapping with NO store lock held (each payload byte moves exactly
        once, source buffer -> segment), then the entry is sealed and
        published.  Concurrent puts of different objects overlap their
        bulk copies; the lock only guards table bookkeeping."""
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        native_eligible = (self._native is not None
                           and isinstance(data, SerializedObject))
        with self._lock:
            existing = self._entries.get(object_id)
            if existing is not None:
                if existing.sealed:
                    return existing.size
                # Another putter is mid-copy: wait for its seal
                # (idempotent re-put, plasma create-in-progress reply).
                self._wait_sealed_locked(object_id)
                existing = self._entries.get(object_id)
                if existing is not None:
                    # Sealed: idempotent success with the winner's size.
                    # Still unsealed after the wait: stuck writer —
                    # don't double-store under it.
                    return existing.size if existing.sealed else size
                # Deleted mid-copy: the winner's bytes are gone — fall
                # through and store OUR copy (returning success with no
                # stored value would surface as a spurious ObjectLost).
            self._ensure_capacity(size)
            reservation = None
            if native_eligible:
                reservation = self._reserve_native_locked(
                    object_id, data.flat_nbytes)
            e = _Entry(data=None if reservation is not None else data,
                       size=size)
            e.sealed = reservation is None
            e.pin_count = 1 if pin else 0
            self._entries[object_id] = e
            self._used += size
            if reservation is None:
                self._lock.notify_all()
                return size
        # Bulk copy OUTSIDE the lock.
        self._fill_reservation(object_id, e, data, reservation)
        return size

    def _fill_reservation(self, object_id: ObjectID, e: _Entry, data,
                          reservation) -> None:
        key = object_id.binary()
        nbytes, offset = reservation
        handle = None
        if offset == _ADOPT:
            # The key was already sealed in the segment (worker-written
            # return re-put): adopt it, no copy.
            handle = _NativeHandle(self._native, key, nbytes)
        else:
            try:
                data.write_into(self._native.view(offset, nbytes))
                self._native.seal(key)
                handle = _NativeHandle(self._native, key, nbytes)
                self.stats["native_put_bytes"] += nbytes
                self.stats["native_puts"] += 1
            except Exception:
                try:
                    self._native.delete(key)
                except Exception:
                    pass
        with self._lock:
            if self._entries.get(object_id) is not e:
                # Deleted while mid-copy: drop the orphaned native block.
                if handle is not None and offset != _ADOPT:
                    handle.delete()
                return
            e.data = handle if handle is not None else data
            e.sealed = True
            self._lock.notify_all()

    def _wait_sealed_locked(self, object_id: ObjectID,
                            timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            e = self._entries.get(object_id)
            if e is None or e.sealed:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._lock.wait(timeout=min(remaining, 0.5))

    def _reserve_native_locked(self, object_id: ObjectID, nbytes: int):
        """Reserve a segment block with the create-request retry flow
        (create_request_queue.h parity): on OOM, ask the native LRU for
        victims, spill them through the Python IO path, and retry;
        returns ``(nbytes, offset)``, ``(nbytes, _ADOPT)`` when the key
        is already sealed natively, or None (python-held buffers, the
        fallback allocation) only when the segment genuinely cannot fit
        the object.  Must hold the store lock."""
        key = object_id.binary()
        need = nbytes + 128
        for attempt in range(4):   # 3 escalations + final retry
            try:
                off = self._native.create(key, nbytes)
                if off is None:
                    # Duplicate key: adopt if sealed, else give up.
                    loc = self._native.locate(key)
                    return (loc[1], _ADOPT) if loc is not None else None
                return (nbytes, off)
            except MemoryError:
                free = self._native.capacity - self._native.used_bytes()
                # Escalating eviction: first the byte shortfall, then a
                # full object's worth of LRU neighbours (total free can
                # exceed the request while no HOLE fits it), finally
                # everything evictable — coalescing then yields the
                # largest hole the pinned islands allow.
                if attempt == 0:
                    shortfall = max(1, need - free)
                elif attempt == 1:
                    shortfall = need
                else:
                    shortfall = self._native.capacity
                victims = self._native.choose_victims(shortfall)
                if not victims:
                    return None
                for vkey in victims:
                    voi = ObjectID(vkey)
                    ve = self._entries.get(voi)
                    if ve is not None and isinstance(ve.data, _NativeHandle):
                        self._spill(voi, ve)     # reads + frees native
                        self.stats["evicted_objects"] += 1
                    else:
                        self._native.delete(vkey)
            except Exception:
                return None
        return None

    def reserve_native(self, object_id: ObjectID, nbytes: int):
        """Public reservation surface (worker-return shm_create): runs
        the same eviction-retry flow under the store lock; returns the
        block offset or None."""
        if self._native is None:
            return None
        with self._lock:
            r = self._reserve_native_locked(object_id, nbytes)
        if r is None or r[1] == _ADOPT:
            return None
        return r[1]

    def create_transfer_writer(self, object_id: ObjectID, nbytes: int,
                               pin: bool = False):
        """Writer for an incoming transfer (pull path): reserves a
        segment block the chunk pipeline assembles into directly, and on
        seal registers the entry + wakes waiters — no intermediate
        ``bytearray``.  Falls back to a heap buffer when no native
        backend is attached or the segment cannot fit the object.

        The store budget is enforced HERE (spilling as needed, raising
        ObjectStoreFullError when even spilling cannot make room) and
        the bytes stay charged to ``_transfer_reserved`` until
        seal/abort, so N concurrent pulls cannot collectively
        over-commit what a single put could not."""
        with self._lock:
            self._ensure_capacity(nbytes)
            self._transfer_reserved += nbytes
            r = None
            if self._native is not None:
                try:
                    r = self._reserve_native_locked(object_id, nbytes)
                except BaseException:
                    self._transfer_reserved -= nbytes
                    raise
        if r is not None and r[1] != _ADOPT:
            return _SegmentTransferWriter(self, object_id, nbytes,
                                          r[1], pin)
        return _HeapTransferWriter(self, object_id, nbytes, pin)

    def _release_transfer_reservation(self, nbytes: int) -> None:
        with self._lock:
            self._transfer_reserved -= nbytes

    def register_native_entry(self, object_id: ObjectID, size: int):
        """Adopt an object a CLIENT created+sealed directly in the
        native segment (worker-written return): table entry wrapping
        the native handle, owner-pinned like any primary copy."""
        with self._lock:
            if object_id in self._entries:
                return
            self._ensure_capacity(size)
            e = _Entry(data=_NativeHandle(self._native,
                                          object_id.binary(), size),
                       size=size)
            e.pin_count = 1
            self._entries[object_id] = e
            self._used += size
            self._lock.notify_all()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                # Unsealed = a put's bulk copy is still in flight; the
                # bytes are not readable yet (plasma: Get sees sealed
                # objects only).
                return None
            e.last_access = time.monotonic()
            if e.data is None and e.spilled_path is not None:
                self._restore(object_id, e)
            return e

    def get_serialized(self, object_id: ObjectID) -> Optional[SerializedObject]:
        e = self.get(object_id)
        if e is None:
            return None
        data = e.data
        if isinstance(data, _NativeHandle):
            return SerializedObject.from_bytes(data.read())
        if isinstance(data, DeviceObject):
            return data.to_serialized()
        return data

    def pin(self, object_id: ObjectID):
        """Store-level pin: protects from Python-side spill selection.
        Native pins are CLIENT pins only (shm surface) — they defer the
        native free while a worker reads through its mapping."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pin_count += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pin_count > 0:
                e.pin_count -= 1

    def delete(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            # An entry holds store budget while its bytes are in memory
            # (data set) or reserved by an in-flight put (unsealed
            # placeholder); spilled entries released theirs at spill.
            if e.data is not None or (not e.sealed
                                      and e.spilled_path is None):
                self._used -= e.size
            if isinstance(e.data, _NativeHandle):
                # Client (worker-held) pins defer the actual free.
                e.data.delete()
            if e.spilled_path:
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass

    # ---- capacity / spilling -------------------------------------------
    def _ensure_capacity(self, incoming: int):
        # Must hold lock.  Spill least-recently-used unpinned-or-pinned
        # entries until the incoming object fits under the threshold.
        # In-flight transfer reservations count as used: their chunks
        # have not landed yet but the bytes are committed.
        limit = int(self.capacity * self.spill_threshold)
        if self._used + self._transfer_reserved + incoming <= limit:
            return
        candidates = sorted(
            ((e.last_access, oid) for oid, e in self._entries.items()
             if e.data is not None and e.sealed and not e.is_device),
            key=lambda t: t[0])
        for _, oid in candidates:
            if self._used + self._transfer_reserved + incoming <= limit:
                break
            self._spill(oid, self._entries[oid])
        if self._used + self._transfer_reserved + incoming > self.capacity:
            raise exceptions.ObjectStoreFullError(
                f"Object of {incoming} bytes exceeds store capacity "
                f"({self._used}/{self.capacity} used, "
                f"{self._transfer_reserved} reserved by in-flight "
                f"transfers; spilling exhausted)")

    def _spill(self, object_id: ObjectID, e: _Entry):
        data = e.data
        path = os.path.join(self.spill_dir, object_id.hex())
        if isinstance(data, _NativeHandle):
            # Stream the segment view straight to disk, THEN free: the
            # view is invalid once the allocator reuses the block.  (A
            # client-pinned object's native free defers to its last
            # release; the spill copy is taken regardless.)
            view = data.read()
            nbytes = view.nbytes
            with open(path, "wb") as f:
                f.write(view)
            del view
            data.delete()
        else:
            if isinstance(data, DeviceObject):
                data = data.to_serialized()
            nbytes = data.flat_nbytes
            with open(path, "wb") as f:
                f.write(data.to_bytes())
        e.spilled_path = path
        e.data = None
        self._used -= e.size
        self.stats["spilled_bytes"] += nbytes
        self.stats["spilled_objects"] += 1

    def _restore(self, object_id: ObjectID, e: _Entry):
        with open(e.spilled_path, "rb") as f:
            blob = f.read()
        e.data = SerializedObject.from_bytes(blob)
        self._used += e.size
        self.stats["restored_bytes"] += len(blob)
        self.stats["restored_objects"] += 1

    def spill_now(self) -> int:
        """Force-spill all unpinned entries (test/chaos hook)."""
        n = 0
        with self._lock:
            for oid, e in list(self._entries.items()):
                if e.data is not None and e.sealed and not e.is_device:
                    self._spill(oid, e)
                    n += 1
        return n

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)


class InPlasmaMarker:
    """Memory-store marker: the value's bytes live in a node store.

    Sealed into the owner's memory store when a large return value lands in
    a node store, so owner-side waits unblock promptly (the reference's
    "in plasma" error-code reply on the Get path).
    """

    __slots__ = ("node_id", "total_bytes")

    def __init__(self, node_id):
        self.node_id = node_id
        self.total_bytes = 0


#: Reservation sentinel: the key is already sealed in the segment —
#: adopt the existing block instead of copying.
_ADOPT = -1


class _NativeHandle:
    """Handle to an object held by the native C++ shm store."""

    __slots__ = ("store", "key", "nbytes")

    def __init__(self, store, key: bytes, nbytes: int):
        self.store = store
        self.key = key
        self.nbytes = nbytes

    def read(self) -> bytes:
        return self.store.get(self.key)

    def delete(self):
        try:
            self.store.delete(self.key)
        except Exception:
            pass


class _SegmentTransferWriter:
    """Incoming-transfer sink over a reserved shm block: the chunk
    pipeline writes each arriving chunk straight into the segment at
    its final offset (ObjectBufferPool chunk assembly without the
    intermediate ``bytearray``); ``seal`` publishes the entry."""

    __slots__ = ("_store", "_object_id", "nbytes", "_offset", "_pin",
                 "_view", "_reserved")

    def __init__(self, store: "NodeObjectStore", object_id: ObjectID,
                 nbytes: int, offset: int, pin: bool):
        self._store = store
        self._object_id = object_id
        self.nbytes = nbytes
        self._offset = offset
        self._pin = pin
        self._view = store._native.view(offset, nbytes)
        self._reserved = True

    def write(self, offset: int, data) -> None:
        from ray_tpu._private.serialization import copy_into_view
        copy_into_view(self._view, offset, data)

    def _release(self) -> None:
        if self._reserved:
            self._reserved = False
            self._store._release_transfer_reservation(self.nbytes)

    def seal(self) -> None:
        store = self._store
        key = self._object_id.binary()
        self._view = None
        store._native.seal(key)
        with store._lock:
            if self._reserved:
                self._reserved = False
                store._transfer_reserved -= self.nbytes
            existing = store._entries.get(self._object_id)
            if existing is not None:
                # Lost a materialization race; keep the winner unless it
                # is (now) backed by this very block.
                if not (isinstance(existing.data, _NativeHandle)
                        and existing.data.key == key):
                    store._native.delete(key)
                return
            e = _Entry(data=_NativeHandle(store._native, key, self.nbytes),
                       size=self.nbytes)
            e.pin_count = 1 if self._pin else 0
            store._entries[self._object_id] = e
            store._used += self.nbytes
            store._lock.notify_all()

    def abort(self) -> None:
        self._view = None
        self._release()
        try:
            self._store._native.delete(self._object_id.binary())
        except Exception:
            pass


class _HeapTransferWriter:
    """Fallback transfer sink when no native segment is available (or
    the object exceeds it): assembles on the heap, seals via a normal
    store put."""

    __slots__ = ("_store", "_object_id", "nbytes", "_pin", "_buf",
                 "_reserved")

    def __init__(self, store: "NodeObjectStore", object_id: ObjectID,
                 nbytes: int, pin: bool):
        self._store = store
        self._object_id = object_id
        self.nbytes = nbytes
        self._pin = pin
        self._buf = bytearray(nbytes)
        self._reserved = True

    def write(self, offset: int, data) -> None:
        self._buf[offset:offset + len(data)] = data

    def _release(self) -> None:
        if self._reserved:
            self._reserved = False
            self._store._release_transfer_reservation(self.nbytes)

    def seal(self) -> None:
        restored = SerializedObject.from_bytes(bytes(self._buf))
        self._buf = None
        self._release()         # put() re-charges _used itself
        self._store.put(self._object_id, restored, pin=self._pin)

    def abort(self) -> None:
        self._buf = None
        self._release()


def segment_chunk_source(store: "NodeObjectStore"):
    """``get_source`` hook for :class:`ray_tpu.rpc.chunked.ChunkServer`:
    serve outgoing transfers straight from the store's shm segment under
    a native pin (released when the session closes), so the SENDER never
    flattens the object either."""

    def get_source(oid_bin: bytes):
        native = store._native if store is not None else None
        if native is None:
            return None
        entry = store.get(ObjectID(oid_bin))
        if entry is None or not isinstance(entry.data, _NativeHandle):
            return None
        key = entry.data.key
        if not native.pin(key):
            return None              # freed in the window
        view = native.get(key)
        if view is None:
            native.unpin(key)
            return None
        return view, lambda: native.unpin(key)

    return get_source


def entry_value(entry: _Entry):
    """Deserialize an entry to its Python value (raising stored errors)."""
    if entry.error is not None:
        raise entry.error
    data = entry.data
    if isinstance(data, DeviceObject):
        return data.value
    if isinstance(data, _NativeHandle):
        return deserialize(SerializedObject.from_bytes(data.read()))
    return deserialize(data)
