"""Per-process and per-node object stores.

Parity targets:
  * ``CoreWorkerMemoryStore`` (reference
    ``src/ray/core_worker/store_provider/memory_store/``) — in-process store
    for small objects and pending futures; blocking ``Get`` with timeout.
  * Plasma (reference ``src/ray/object_manager/plasma/`` — shared-memory store
    with capacity accounting, pinning, LRU eviction and spill-to-disk via
    ``raylet/local_object_manager.cc``).  Here :class:`NodeObjectStore` is the
    plasma equivalent: host-memory slab per node, optional native C++
    shared-memory backend (``ray_tpu/native``), spill/restore to the session
    dir, and a **device-object extension** the reference never had — jax
    device buffers can live in the store without a host copy and are only
    materialized to host when crossing nodes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ray_tpu import exceptions
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject, deserialize


class DeviceObject:
    """A store entry whose payload is a jax device array (or pytree).

    Zero-copy handoff: actors on the same node exchange the device buffer
    directly; a host copy happens only on spill or cross-node transfer.
    This is the TPU-native extension of plasma (SURVEY.md §7 "hard parts").
    """

    __slots__ = ("value", "nbytes")

    def __init__(self, value):
        import jax
        self.value = value
        self.nbytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(value)
            if hasattr(x, "dtype"))

    def to_serialized(self) -> SerializedObject:
        from ray_tpu._private.serialization import serialize
        return serialize(self.value)


class _Entry:
    __slots__ = ("data", "error", "size", "pin_count", "last_access",
                 "spilled_path", "sealed", "is_device")

    def __init__(self, data=None, error=None, size=0):
        self.data = data              # SerializedObject | DeviceObject | None
        self.error = error            # Exception to raise at get()
        self.size = size
        self.pin_count = 0
        self.last_access = time.monotonic()
        self.spilled_path: Optional[str] = None
        self.sealed = data is not None or error is not None
        self.is_device = isinstance(data, DeviceObject)


class MemoryStore:
    """In-process store: small objects, error markers, pending futures.

    ``get`` blocks on a condition variable until the object is sealed
    (reference: memory store ``GetAsync``/``Get`` with timeout).
    """

    def __init__(self):
        self._lock = threading.Condition()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._get_callbacks: Dict[ObjectID, list] = {}

    def put(self, object_id: ObjectID, data, error=None) -> int:
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.sealed:
                return entry.size  # idempotent re-put
            entry = _Entry(data=data, error=error, size=size)
            self._entries[object_id] = entry
            callbacks = self._get_callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(entry)
        return size

    def put_error(self, object_id: ObjectID, error: BaseException):
        self.put(object_id, None, error=error)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._entries.get(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> _Entry:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                e = self._entries.get(object_id)
                if e is not None and e.sealed:
                    e.last_access = time.monotonic()
                    return e
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise exceptions.GetTimeoutError(
                        f"Get timed out for {object_id}")
                self._lock.wait(timeout=remaining if remaining is None
                                else min(remaining, 0.5))

    def get_async(self, object_id: ObjectID, cb: Callable[[_Entry], None]):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.sealed:
                pass
            else:
                self._get_callbacks.setdefault(object_id, []).append(cb)
                return
        cb(e)

    def cancel_get_async(self, object_id: ObjectID,
                         cb: Callable[[_Entry], None]):
        """Deregister a pending get_async callback (no-op if it already
        fired) — callers that stop waiting must not leak closures."""
        with self._lock:
            cbs = self._get_callbacks.get(object_id)
            if cbs is None:
                return
            try:
                cbs.remove(cb)
            except ValueError:
                return
            if not cbs:
                del self._get_callbacks[object_id]

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._entries.pop(object_id, None)
            self._get_callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


class NodeObjectStore:
    """Plasma-equivalent per-node store with capacity, pinning and spilling.

    Reference behaviors kept: create/seal lifecycle, primary-copy pinning
    (``local_object_manager.h:37``), spill-over-threshold with batched
    writes, restore-on-demand, delete-when-out-of-scope, fallback allocation
    never fails hard (OOM raises only if spilling cannot reclaim).
    """

    def __init__(self, node_id, capacity_bytes: int, spill_dir: str,
                 spill_threshold: float = 0.8, native_backend=None):
        self.node_id = node_id
        self.capacity = capacity_bytes
        self.spill_threshold = spill_threshold
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Condition()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._used = 0
        self._native = native_backend  # ray_tpu.native shm store, optional
        self.stats = {"spilled_bytes": 0, "restored_bytes": 0,
                      "spilled_objects": 0, "restored_objects": 0,
                      "evicted_objects": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        nid = getattr(node_id, "hex", lambda: str(node_id))()[:12]

        def _collect(store):
            labels = {"node": nid}
            record_internal("ray_tpu.object_store.used_bytes",
                            store._used, **labels)
            record_internal("ray_tpu.object_store.capacity_bytes",
                            store.capacity, **labels)
            record_internal("ray_tpu.object_store.num_objects",
                            len(store._entries), **labels)
            for k, v in store.stats.items():
                record_internal(f"ray_tpu.object_store.{k}", v, **labels)
        get_metrics_registry().register_collector(self, _collect)

    # ---- create/seal (plasma lifecycle) --------------------------------
    def put(self, object_id: ObjectID, data, pin: bool = True) -> int:
        size = getattr(data, "total_bytes", None) or getattr(data, "nbytes", 0)
        with self._lock:
            if object_id in self._entries and self._entries[object_id].sealed:
                return self._entries[object_id].size
            self._ensure_capacity(size)
            e = _Entry(data=data, size=size)
            e.pin_count = 1 if pin else 0
            if self._native is not None and isinstance(data, SerializedObject) \
                    and not e.is_device:
                handle = self._native_put(object_id, data.to_bytes())
                if handle is not None:
                    e.data = handle
            self._entries[object_id] = e
            self._used += size
            self._lock.notify_all()
            return size

    def _native_put(self, object_id: ObjectID, blob: bytes):
        """Native put with the create-request retry flow
        (create_request_queue.h parity): on OOM, ask the native LRU for
        victims, spill them through the Python IO path, and retry;
        returns None (python-held buffers, the fallback allocation)
        only when the segment genuinely cannot fit the object.  Must
        hold the store lock."""
        key = object_id.binary()
        need = len(blob) + 128
        for attempt in range(4):   # 3 escalations + final retry
            try:
                self._native.put(key, blob)
                return _NativeHandle(self._native, key, len(blob))
            except MemoryError:
                free = self._native.capacity - self._native.used_bytes()
                # Escalating eviction: first the byte shortfall, then a
                # full object's worth of LRU neighbours (total free can
                # exceed the request while no HOLE fits it), finally
                # everything evictable — coalescing then yields the
                # largest hole the pinned islands allow.
                if attempt == 0:
                    shortfall = max(1, need - free)
                elif attempt == 1:
                    shortfall = need
                else:
                    shortfall = self._native.capacity
                victims = self._native.choose_victims(shortfall)
                if not victims:
                    return None
                for vkey in victims:
                    voi = ObjectID(vkey)
                    ve = self._entries.get(voi)
                    if ve is not None and isinstance(ve.data, _NativeHandle):
                        self._spill(voi, ve)     # reads + frees native
                        self.stats["evicted_objects"] += 1
                    else:
                        self._native.delete(vkey)
            except Exception:
                return None
        return None

    def register_native_entry(self, object_id: ObjectID, size: int):
        """Adopt an object a CLIENT created+sealed directly in the
        native segment (worker-written return): table entry wrapping
        the native handle, owner-pinned like any primary copy."""
        with self._lock:
            if object_id in self._entries:
                return
            self._ensure_capacity(size)
            e = _Entry(data=_NativeHandle(self._native,
                                          object_id.binary(), size),
                       size=size)
            e.pin_count = 1
            self._entries[object_id] = e
            self._used += size
            self._lock.notify_all()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            e.last_access = time.monotonic()
            if e.data is None and e.spilled_path is not None:
                self._restore(object_id, e)
            return e

    def get_serialized(self, object_id: ObjectID) -> Optional[SerializedObject]:
        e = self.get(object_id)
        if e is None:
            return None
        data = e.data
        if isinstance(data, _NativeHandle):
            return SerializedObject.from_bytes(data.read())
        if isinstance(data, DeviceObject):
            return data.to_serialized()
        return data

    def pin(self, object_id: ObjectID):
        """Store-level pin: protects from Python-side spill selection.
        Native pins are CLIENT pins only (shm surface) — they defer the
        native free while a worker reads through its mapping."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pin_count += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pin_count > 0:
                e.pin_count -= 1

    def delete(self, object_id: ObjectID):
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            self._used -= e.size if e.data is not None else 0
            if isinstance(e.data, _NativeHandle):
                # Client (worker-held) pins defer the actual free.
                e.data.delete()
            if e.spilled_path:
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass

    # ---- capacity / spilling -------------------------------------------
    def _ensure_capacity(self, incoming: int):
        # Must hold lock.  Spill least-recently-used unpinned-or-pinned
        # entries until the incoming object fits under the threshold.
        limit = int(self.capacity * self.spill_threshold)
        if self._used + incoming <= limit:
            return
        candidates = sorted(
            ((e.last_access, oid) for oid, e in self._entries.items()
             if e.data is not None and not e.is_device),
            key=lambda t: t[0])
        for _, oid in candidates:
            if self._used + incoming <= limit:
                break
            self._spill(oid, self._entries[oid])
        if self._used + incoming > self.capacity:
            raise exceptions.ObjectStoreFullError(
                f"Object of {incoming} bytes exceeds store capacity "
                f"({self._used}/{self.capacity} used; spilling exhausted)")

    def _spill(self, object_id: ObjectID, e: _Entry):
        data = e.data
        if isinstance(data, _NativeHandle):
            # Materialize before freeing: read() is a view into the
            # segment, invalid once the allocator reuses the block.
            # (A client-pinned object's native free defers to its last
            # release; the spill copy is taken regardless.)
            blob = bytes(data.read())
            data.delete()
        elif isinstance(data, DeviceObject):
            blob = data.to_serialized().to_bytes()
        else:
            blob = data.to_bytes()
        path = os.path.join(self.spill_dir, object_id.hex())
        with open(path, "wb") as f:
            f.write(blob)
        e.spilled_path = path
        e.data = None
        self._used -= e.size
        self.stats["spilled_bytes"] += len(blob)
        self.stats["spilled_objects"] += 1

    def _restore(self, object_id: ObjectID, e: _Entry):
        with open(e.spilled_path, "rb") as f:
            blob = f.read()
        e.data = SerializedObject.from_bytes(blob)
        self._used += e.size
        self.stats["restored_bytes"] += len(blob)
        self.stats["restored_objects"] += 1

    def spill_now(self) -> int:
        """Force-spill all unpinned entries (test/chaos hook)."""
        n = 0
        with self._lock:
            for oid, e in list(self._entries.items()):
                if e.data is not None and not e.is_device:
                    self._spill(oid, e)
                    n += 1
        return n

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)


class InPlasmaMarker:
    """Memory-store marker: the value's bytes live in a node store.

    Sealed into the owner's memory store when a large return value lands in
    a node store, so owner-side waits unblock promptly (the reference's
    "in plasma" error-code reply on the Get path).
    """

    __slots__ = ("node_id", "total_bytes")

    def __init__(self, node_id):
        self.node_id = node_id
        self.total_bytes = 0


class _NativeHandle:
    """Handle to an object held by the native C++ shm store."""

    __slots__ = ("store", "key", "nbytes")

    def __init__(self, store, key: bytes, nbytes: int):
        self.store = store
        self.key = key
        self.nbytes = nbytes

    def read(self) -> bytes:
        return self.store.get(self.key)

    def delete(self):
        try:
            self.store.delete(self.key)
        except Exception:
            pass


def entry_value(entry: _Entry):
    """Deserialize an entry to its Python value (raising stored errors)."""
    if entry.error is not None:
        raise entry.error
    data = entry.data
    if isinstance(data, DeviceObject):
        return data.value
    if isinstance(data, _NativeHandle):
        return deserialize(SerializedObject.from_bytes(data.read()))
    return deserialize(data)
