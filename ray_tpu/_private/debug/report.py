"""Per-OS-process introspection report — the ``debug_dump`` RPC body.

One dict per process, assembled from the always-on debug plane:
watchdog loop snapshots (busy/idle, current handler, queue depth, wedge
state), wedge reports, lock-contention rollup, flight-recorder tail,
swallowed-exception counts and (optionally) every thread's stack.  The
head's ``debug_dump`` handler fans this out across the cluster and
``ray-tpu doctor`` renders it.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from ray_tpu._private.debug import (flight_recorder, lock_order, swallow,
                                    watchdog)


def striped_lock_rollup() -> dict:
    """Aggregate contention stats of lock-striped locks back to their
    base name (``Foo._lock[s03]`` -> ``Foo._lock``).  The per-stripe
    rows stay individually visible in :func:`top_locks`; this rollup is
    the number that compares against pre-striping baselines (the PR 13
    ``TaskEventBuffer._lock`` / ``ReferenceCounter._lock`` waits)."""
    import re
    stripe_re = re.compile(r"\[s\d+\]$")
    snap = lock_order.contention_snapshot()
    out: Dict[str, dict] = {}
    for name, st in snap.items():
        m = stripe_re.search(name)
        if not m:
            continue
        base = name[:m.start()]
        agg = out.setdefault(base, {
            "stripes": 0, "acquires": 0, "contended": 0,
            "wait_total_s": 0.0, "wait_max_s": 0.0})
        agg["stripes"] += 1
        agg["acquires"] += st["acquires"]
        agg["contended"] += st["contended"]
        agg["wait_total_s"] = round(
            agg["wait_total_s"] + st["wait_sum_s"], 6)
        agg["wait_max_s"] = max(agg["wait_max_s"],
                                round(st["wait_max_s"], 6))
    return out


def top_locks(n: int = 5) -> list:
    """The ``n`` hottest locks by total sampled acquire-wait time."""
    snap = lock_order.contention_snapshot()
    rows = []
    for name, st in snap.items():
        rows.append({
            "lock": name,
            "acquires": st["acquires"],
            "contended": st["contended"],
            "wait_total_s": round(st["wait_sum_s"], 6),
            "wait_max_s": round(st["wait_max_s"], 6),
            "hold_max_s": round(st["hold_max_s"], 6),
            "hold_total_s": round(st["hold_sum_s"], 6),
        })
    rows.sort(key=lambda r: r["wait_total_s"], reverse=True)
    return rows[:n]


def build_debug_report(include_stacks: bool = True,
                       tail: int = 50,
                       top_n_locks: int = 8) -> Dict:
    """Assemble this process's introspection report (cheap: snapshot
    reads only — safe to serve from an RPC handler while wedged,
    because none of the sources below take runtime locks)."""
    loops = watchdog.loops_snapshot()
    loops.sort(key=lambda s: (not s["wedged"], -s["busy_for_s"]))
    report = {
        "pid": os.getpid(),
        "ts": time.time(),
        "stall_budget_s": watchdog.stall_budget_s(),
        "loops": loops,
        "wedges": watchdog.wedge_reports(),
        "locks": top_locks(top_n_locks),
        "recorder_tail": flight_recorder.tail(tail),
        "recorder_stats": flight_recorder.stats(),
        "swallowed": swallow.counts(),
    }
    if include_stacks:
        report["stacks"] = watchdog.thread_stacks()
        report["held_locks"] = watchdog.held_locks()
    return report


def handle_debug_dump(payload: Optional[dict]) -> Dict:
    """RPC-handler shape shared by node hosts and the head's own
    process: payload keys ``stacks`` (bool) and ``tail`` (int)."""
    payload = payload or {}
    return build_debug_report(
        include_stacks=bool(payload.get("stacks", True)),
        tail=int(payload.get("tail", 50)))
