"""Runtime concurrency diagnostics (the dynamic half of graftcheck).

Three tools, all zero-cost unless armed by env var:

* :mod:`.lock_order` — ``diag_lock``/``diag_rlock``/``diag_condition``
  factories wrapping ``threading`` primitives with a global
  acquisition-graph witness (``RAY_TPU_LOCK_DIAG=1``); raises on
  lock-order cycle formation and on hold-time over budget.
* :mod:`.thread_registry` — ``@loop_only`` event-loop affinity asserts
  (``RAY_TPU_LOOP_AFFINITY=1``).
* :mod:`.swallow` — accounted exception swallowing for pump loops
  (always on; it is bookkeeping, not a probe).

plus the always-on introspection plane (ISSUE 13):

* :mod:`.flight_recorder` — per-process bounded structured ring over
  the runtime's decision points (tick solves, lease batches, transfer
  source selection, spill/restore/reconstruction, create-queue admits,
  fault firings); dumped by ``ray-tpu doctor``, on watchdog trip, and
  by tests;
* :mod:`.watchdog` — stall watchdog over every event loop and pump
  thread: wedge reports (all thread stacks, held diag-lock sets,
  recorder tail) to a crash file and to the head;
* contention profiling (``RAY_TPU_LOCK_CONTENTION=1``) inside
  :mod:`.lock_order` — sampled per-named-lock acquire-wait and
  hold-time histograms at /metrics, without the witness's cycle
  checks;
* :mod:`.report` — the per-process ``debug_dump`` report the doctor
  CLI renders.

The tier-1 conftest arms the probes AND the watchdog for the whole
suite; the static side lives in ``tools/graftcheck``.
"""

from ray_tpu._private.debug.lock_order import (  # noqa: F401
    DiagLock, DiagRLock, LockHoldBudgetExceeded, LockOrderViolation,
    diag_condition, diag_lock, diag_rlock)
from ray_tpu._private.debug import swallow  # noqa: F401
from ray_tpu._private.debug import flight_recorder  # noqa: F401
from ray_tpu._private.debug.thread_registry import (  # noqa: F401
    LoopAffinityError, current_loop_kind, loop_only, register_current,
    unregister_current)
