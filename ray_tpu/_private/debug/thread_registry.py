"""Event-loop thread identity — the runtime half of graftcheck rule R4.

Methods that mutate scheduler / dispatch state are *loop-affine*: they
are only correct when run on their daemon's event-loop thread (the
reference posts everything through one io_context per daemon;
``node_manager.cc`` handlers never run concurrently with the tick).  In
Python nothing stops a test — or a refactor — from calling them
directly from an arbitrary thread, which is exactly how tick-state races
slip in.

:func:`loop_only` marks such a method.  The static analyzer verifies
every call site is either another ``@loop_only`` function or a
``loop.post``/``schedule_*`` registration; the runtime assert (armed via
``RAY_TPU_LOOP_AFFINITY=1``, on by default in tests through the tier-1
conftest) enforces it on every call.

Loops register by *kind*: an :class:`~ray_tpu._private.event_loop.EventLoop`
named ``raylet-a1b2c3`` registers its thread under kind ``raylet``.  The
check is kind-level, not instance-level — it catches "ran on a worker /
main / pump thread" (the real bug class), while two in-process raylets
ticking each other's managers would pass; instance-level identity would
need the loop handle plumbed through every callee for marginal extra
coverage.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Dict, Optional


class LoopAffinityError(AssertionError):
    """A ``@loop_only`` method ran on a thread outside its loop kind."""


_lock = threading.Lock()
#: thread ident -> loop kind (e.g. "raylet", "gcs").
_loop_threads: Dict[int, str] = {}


def _armed() -> bool:
    return os.environ.get("RAY_TPU_LOOP_AFFINITY", "") == "1"


def register_current(loop_name: str) -> None:
    """Register the calling thread as the loop thread for ``loop_name``.

    The kind is the name up to the first ``-`` (loop names embed the
    node id suffix: ``raylet-a1b2c3`` -> kind ``raylet``)."""
    kind = loop_name.split("-", 1)[0]
    with _lock:
        _loop_threads[threading.get_ident()] = kind


def unregister_current() -> None:
    with _lock:
        _loop_threads.pop(threading.get_ident(), None)


def current_loop_kind() -> Optional[str]:
    return _loop_threads.get(threading.get_ident())


def loop_only(kind: str):
    """Decorator: assert the wrapped method runs on a ``kind`` loop thread.

    The marker attribute ``__loop_only__`` is what graftcheck's R4 keys
    on statically; the wrapper is the runtime enforcement."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _armed():
                got = _loop_threads.get(threading.get_ident())
                if got != kind:
                    raise LoopAffinityError(
                        f"{fn.__qualname__} is @loop_only({kind!r}) but "
                        f"ran on thread "
                        f"{threading.current_thread().name!r} "
                        f"(registered loop kind: {got!r}) — post it to "
                        f"the {kind} event loop instead of calling it "
                        f"directly")
            return fn(*args, **kwargs)

        wrapper.__loop_only__ = kind
        return wrapper

    return deco
