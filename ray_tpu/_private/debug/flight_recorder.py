"""Per-process flight recorder: a bounded structured ring over the
runtime's DECISION points.

The metrics plane (PR 8) counts what happened; this ring remembers the
last N decisions verbatim — scheduler tick solve summaries, lease-batch
grant/backlog vectors, transfer source selections and relay-chain
choices, spill/restore/reconstruction attempts, create-queue admits,
fault firings — so "why is it stuck / why did it go THERE" is
answerable after the fact without re-running under tracing.  Parity:
the reference's ``RAY_EVENT`` ring + ``ray debug`` dump of recent
scheduler events (event.h bounded in-memory sink).

Design constraints, in order:

* **cheap on the hot path** — one non-blocking lock attempt and three
  slot writes per record; a contended recorder DROPS the record and
  bumps a counter rather than ever making a caller wait;
* **bounded** — fixed slot count (``flight_recorder_slots``), the ring
  overwrites oldest; slot payloads are replaced, never accumulated;
* **always on** — recording is the default (``flight_recorder_enabled``)
  because the whole point is having the tail when something wedges
  unexpectedly; ``record()`` degrades to one dict read when disabled.

Dumped on demand (``debug_dump`` RPC / ``ray-tpu doctor``), on watchdog
trip (the wedge report carries :func:`tail`), and by tests.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

_DEFAULT_SLOTS = 512

# The ring: preallocated fixed-size slot list.  Each slot is a 3-list
# [wall_ts, category, fields_dict] mutated in place — steady state
# allocates only the caller's kwargs dict.
_lock = threading.Lock()        # debug-plane internal; exempt from R8
_slots: List[list] = [[0.0, "", None] for _ in range(_DEFAULT_SLOTS)]
_next = 0                       # next slot index to overwrite
_written = 0                    # total records accepted
_dropped = 0                    # records lost to recorder contention
_enabled: Optional[bool] = None  # lazily read from config
_sized = False                   # ring sized (from config or configure())


def _peek_config():
    """The config singleton WITHOUT get_config(): get_config takes
    config._lock, which is itself a diag lock — a record() fired from
    inside a lock acquire (the ``lock.hold`` fault hook) re-entering
    get_config would self-deadlock on the non-reentrant inner lock.
    A racy unlocked read is exactly right here: worst case None, and
    we stay on defaults until the singleton exists."""
    try:
        from ray_tpu._private import config as config_mod
        return config_mod._global_config
    except Exception:
        return None


def _is_enabled() -> bool:
    global _enabled, _sized
    if _enabled is None:
        cfg = _peek_config()
        if cfg is None:
            return True         # default-on until config initializes
        _enabled = bool(cfg.flight_recorder_enabled)
        if _enabled and not _sized:
            try:
                configure(slots=cfg.flight_recorder_slots)
            except Exception:
                pass
    return _enabled


def configure(enabled: Optional[bool] = None,
              slots: Optional[int] = None) -> None:
    """Resize / toggle the ring (tests, bench arms).  Resizing clears
    it — slot records are positional, not copyable across sizes.  An
    explicit size wins over the lazy config-derived one."""
    global _enabled, _slots, _next, _sized
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if slots is not None and slots > 0:
            _sized = True
            if slots != len(_slots):
                global _written, _dropped
                _slots = [[0.0, "", None] for _ in range(int(slots))]
                _next = 0
                # Resizing clears the ring — the counters must follow,
                # or tail() walks never-written slots as phantom rows.
                _written = 0
                _dropped = 0


def record(category: str, **fields) -> None:
    """Append one decision record.  Never blocks, never raises: on
    recorder contention the record is dropped and counted."""
    global _next, _written, _dropped
    if _enabled is False or (_enabled is None and not _is_enabled()):
        return
    if not _lock.acquire(blocking=False):
        _dropped += 1           # GIL-atomic enough for a diagnostic
        return
    try:
        slot = _slots[_next]
        slot[0] = time.time()
        slot[1] = category
        slot[2] = fields
        _next = (_next + 1) % len(_slots)
        _written += 1
    finally:
        _lock.release()


def tail(n: Optional[int] = None) -> List[Dict]:
    """Last ``n`` records (default: whole ring), oldest first."""
    with _lock:
        size = len(_slots)
        count = min(_written, size)
        if n is not None:
            count = min(count, max(0, int(n)))
        out = []
        idx = (_next - count) % size
        for _ in range(count):
            ts, cat, fields = _slots[idx]
            row = {"ts": ts, "cat": cat}
            if fields:
                row.update(fields)
            out.append(row)
            idx = (idx + 1) % size
        return out


def stats() -> Dict[str, int]:
    with _lock:
        return {"written": _written, "dropped": _dropped,
                "capacity": len(_slots)}


def reset() -> None:
    """Clear ring + counters (test isolation)."""
    global _next, _written, _dropped
    with _lock:
        for slot in _slots:
            slot[0], slot[1], slot[2] = 0.0, "", None
        _next = 0
        _written = 0
        _dropped = 0
