"""Accounted exception swallowing for daemon pump loops.

A daemon pump loop (dispatch pool, heartbeat sender, chunk server) must
survive a bad callback — but ``except Exception: pass`` destroys the
evidence: graftcheck rule R7 flags exactly that shape because both PR-2
and PR-6 root-cause hunts lost hours to errors that had been eaten by a
pump loop.

:func:`noted` is the sanctioned replacement: the loop stays alive, the
error is counted per site (:func:`count` — tests assert on it) and the
first occurrence per site is logged with a traceback (first-only, so a
hot loop hitting the same broken callback cannot flood stderr).
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict

_lock = threading.Lock()
_counts: Dict[str, int] = {}
_logged: Dict[str, bool] = {}


def noted(site: str, exc: BaseException) -> None:
    """Record a deliberately-swallowed exception at ``site``.

    Call from an ``except Exception as e:`` handler in a loop that must
    not die.  Never raises."""
    try:
        with _lock:
            _counts[site] = _counts.get(site, 0) + 1
            first = not _logged.get(site)
            _logged[site] = True
        if first:
            print(f"[ray_tpu] swallowed exception at {site} "
                  f"(logged once; see debug.swallow.count): "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            # Print exc ITSELF, not "the current exception": noted()
            # may be handed a stored error outside any except block
            # (captured on one thread, reported on another).
            traceback.print_exception(type(exc), exc, exc.__traceback__,
                                      file=sys.stderr)
    except Exception:
        pass  # the accounting itself must never take the pump down


def count(site: str) -> int:
    """Swallowed-exception count for ``site`` (0 if never hit)."""
    with _lock:
        return _counts.get(site, 0)


def counts() -> Dict[str, int]:
    with _lock:
        return dict(_counts)


def reset() -> None:
    with _lock:
        _counts.clear()
        _logged.clear()
