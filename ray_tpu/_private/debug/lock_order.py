"""Runtime lock-order witness — the dynamic half of graftcheck.

Parity: the reference runs plasma/raylet under TSan (SURVEY §5.2), whose
deadlock detector reports *potential* lock-order inversions from a single
run, not just ones that actually deadlocked.  Python has no TSan, so this
module wraps ``threading.Lock/RLock/Condition`` behind factory functions
(:func:`diag_lock` / :func:`diag_rlock` / :func:`diag_condition`) that are
zero-cost pass-throughs returning the plain ``threading`` primitive unless
``RAY_TPU_LOCK_DIAG=1`` is set at creation time.

Armed, every acquisition is recorded against a **name-level** global
acquisition graph (all instances created at one call site share a node, so
an ABBA order between two *differently named* locks is caught regardless
of which instances were involved — exactly the shape of the PR-6
store-lock -> refcount-lock deadlock).  Reentrancy is tracked by lock
*instance*: re-acquiring the same object bumps a depth counter, while
nesting two different instances of the same name (hierarchical
same-class locking, deadlock-free only under a global instance order
the name-level graph cannot see) is recorded as a self-edge,
observable via :func:`same_name_nestings` but never raised on — the
static analyzer's R1 self-edge check covers the non-reentrant case.
The witness raises
:class:`LockOrderViolation` the moment an edge closes a cycle, and
:class:`LockHoldBudgetExceeded` when a lock is held longer than
``RAY_TPU_LOCK_HOLD_BUDGET_S`` (0 = unlimited, the default: tier-1 boxes
can stall multi-second under sanitizer compiles, so the budget is an
opt-in probe, not an always-on gate).

The tier-1 conftest arms the witness for the whole suite, so every
existing test doubles as a lock-order probe.

Cost when armed: the steady-state acquire path is thread-local list ops
plus one dict read (edge dedup); the internal registry lock is taken only
when a *new* edge is inserted, which happens a bounded number of times
per process (#locks is small and fixed).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the global acquisition graph."""


class LockHoldBudgetExceeded(RuntimeError):
    """A lock was held longer than the configured hold budget."""


def _armed() -> bool:
    return os.environ.get("RAY_TPU_LOCK_DIAG", "") == "1"


# One-entry memo for the hold budget: releases are a hot path, so the
# float parse runs only when the env string actually changes (tests
# monkeypatch it; production sets it once).
_budget_memo: Tuple[Optional[str], float] = (None, -1.0)


def _hold_budget_s() -> float:
    global _budget_memo
    raw = os.environ.get("RAY_TPU_LOCK_HOLD_BUDGET_S", "0")
    memo_raw, memo_val = _budget_memo
    if raw == memo_raw:
        return memo_val
    try:
        val = float(raw)
    except ValueError:
        val = 0.0
    _budget_memo = (raw, val)
    return val


# ---------------------------------------------------------------------------
# Global acquisition graph (name-level).
#
# _edges maps (held_name, acquired_name) -> short provenance string for the
# first time the edge was observed.  Reads are plain dict lookups (GIL-safe,
# no lock); inserts take _graph_lock and run the cycle check.
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], str] = {}
_succ: Dict[str, List[str]] = {}
#: Cycles reported so far (kept after raise so the conftest / a test
#: harness can assert "no cycle reports" over a whole run).
_violations: List[str] = []

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _site(skip: int = 2) -> str:
    """file:line of the acquiring frame, skipping witness internals AND
    threading.py (a `with cond:` acquires via Condition.__enter__, whose
    frame says nothing about the caller)."""
    for fs in reversed(traceback.extract_stack(limit=skip + 8)[:-skip]):
        fn = fs.filename.replace(os.sep, "/")
        if "debug/lock_order" in fn or fn.endswith("/threading.py"):
            continue
        return f"{os.path.basename(fs.filename)}:{fs.lineno}"
    return "?"


def _stack_summary(depth: int = 12) -> str:
    """Compact call-path provenance for a NEW edge (bounded: edges are
    recorded once per (held, acquired) pair, so the cost is one-time)."""
    frames = []
    for fs in traceback.extract_stack(limit=depth + 4)[:-2]:
        fn = fs.filename.replace(os.sep, "/")
        if "debug/lock_order" in fn or fn.endswith("/threading.py"):
            continue
        frames.append(
            f"{os.path.basename(fs.filename)}:{fs.lineno}:{fs.name}")
    return " <- ".join(reversed(frames[-depth:]))


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _succ; returns a node path src..dst or None."""
    seen = {src}
    path = [src]

    def walk(node: str) -> bool:
        for nxt in _succ.get(node, ()):
            if nxt == dst:
                path.append(nxt)
                return True
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if walk(nxt):
                return True
            path.pop()
        return False

    return path if walk(src) else None


def _record_edge(held: str, acquired: str,
                 raise_on_cycle: bool = True) -> None:
    key = (held, acquired)
    if key in _edges:          # steady-state fast path: no lock
        return
    site = _stack_summary()
    with _graph_lock:
        if key in _edges:
            return
        # Adding held->acquired closes a cycle iff acquired already
        # reaches held.
        back = _find_path(acquired, held)
        _edges[key] = site
        _succ.setdefault(held, []).append(acquired)
        if back is None:
            return
        cycle = back + [acquired]
        legs = []
        for a, b in zip(cycle, cycle[1:]):
            legs.append(f"  {a} -> {b}  (first seen at "
                        f"{_edges.get((a, b), site)})")
        msg = ("lock-order cycle formed: "
               + " -> ".join(cycle) + "\n" + "\n".join(legs)
               + f"\n  closing edge {held} -> {acquired} acquired at {site}")
        _violations.append(msg)
    if raise_on_cycle:
        raise LockOrderViolation(msg)


#: name -> count of cross-instance same-name nestings observed.
_same_name: Dict[str, int] = {}


def _note_same_name_nesting(name: str) -> None:
    with _graph_lock:
        _same_name[name] = _same_name.get(name, 0) + 1


def same_name_nestings() -> Dict[str, int]:
    """Locks whose instances were nested inside each other (per name).
    Not a violation by itself — safe under a global instance order —
    but the place to look first when a same-class deadlock is
    suspected."""
    with _graph_lock:
        return dict(_same_name)


def violations() -> List[str]:
    """Cycle reports recorded so far (for harness-level assertions)."""
    with _graph_lock:
        return list(_violations)


def reset() -> None:
    """Clear the global graph and reports (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _succ.clear()
        _violations.clear()
        _same_name.clear()


def snapshot() -> tuple:
    """Copy of the global graph state — pair with :func:`restore` so a
    test that deliberately forms a cycle doesn't leave the report (or
    its edges) behind for the rest of the suite."""
    with _graph_lock:
        return (dict(_edges), {k: list(v) for k, v in _succ.items()},
                list(_violations), dict(_same_name))


def restore(state: tuple) -> None:
    edges, succ, violations, same_name = state
    with _graph_lock:
        _edges.clear()
        _edges.update(edges)
        _succ.clear()
        _succ.update({k: list(v) for k, v in succ.items()})
        _violations.clear()
        _violations.extend(violations)
        _same_name.clear()
        _same_name.update(same_name)


def graph_edges() -> Dict[Tuple[str, str], str]:
    with _graph_lock:
        return dict(_edges)


# ---------------------------------------------------------------------------
# Wrappers.


class _DiagBase:
    """Shared acquire/release bookkeeping over an inner threading lock.

    Reentrancy is tracked per-thread by lock INSTANCE: only the
    outermost acquisition of an instance records an edge / stack entry,
    so RLock recursion adds no self-edges, nesting two instances of the
    same name is still observed (``same_name_nestings``), and
    plain-Lock self-deadlocks hang exactly as they would unwrapped
    (the witness never *masks* behavior).
    """

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    # -- bookkeeping ----------------------------------------------------
    # Stack entries: [name, t_acquired, depth, lock_instance_id].
    def _note_acquired(self, raise_on_cycle: bool = True) -> None:
        st = _stack()
        me = id(self)
        for entry in st:
            if entry[3] == me:
                entry[2] += 1          # true reentrancy: same instance
                return
        if st:
            if st[-1][0] == self.name:
                # A DIFFERENT instance of the same name while one is
                # held: hierarchical same-class nesting.  Recorded as a
                # self-edge diagnostic (same_name_nestings), never
                # raised — name-level ordering cannot validate the
                # instance order that makes it safe or not.
                _note_same_name_nesting(self.name)
            else:
                _record_edge(st[-1][0], self.name,
                             raise_on_cycle=raise_on_cycle)
        st.append([self.name, time.monotonic(), 1, me])

    def _note_released(self) -> None:
        st = _stack()
        me = id(self)
        for i in range(len(st) - 1, -1, -1):
            if st[i][3] == me:
                st[i][2] -= 1
                if st[i][2] == 0:
                    held_for = time.monotonic() - st[i][1]
                    del st[i]
                    budget = _hold_budget_s()
                    if budget > 0 and held_for > budget:
                        raise LockHoldBudgetExceeded(
                            f"{self.name} held {held_for:.3f}s "
                            f"(budget {budget:.3f}s), released at "
                            f"{_site()}")
                return

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except LockOrderViolation:
                # Don't strand the inner lock: the caller's `with` body
                # never runs, so nothing would ever release it.
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        self._note_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        try:
            self.release()
        except LockHoldBudgetExceeded:
            # Never mask an in-flight exception from the with-body with
            # the diagnostic — the original error is what the user is
            # debugging; the budget report rides _violations-style logs
            # only when it would otherwise be the sole signal.
            if exc and exc[0] is not None:
                return False
            raise
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self):
        return f"<{type(self).__name__} {self.name} of {self._inner!r}>"


class DiagLock(_DiagBase):
    __slots__ = ()


class DiagRLock(_DiagBase):
    """Adds the private Condition integration hooks so a
    ``threading.Condition`` built over this wrapper keeps bookkeeping
    exact across ``wait()`` (which releases all recursion levels and
    re-acquires them)."""

    __slots__ = ()

    def _release_save(self):
        st = _stack()
        me = id(self)
        depth = 0
        for i in range(len(st) - 1, -1, -1):
            if st[i][3] == me:
                depth = st[i][2]
                del st[i]
                break
        saved = (self._inner._release_save()
                 if hasattr(self._inner, "_release_save")
                 else self._inner.release())
        return (saved, depth)

    def _acquire_restore(self, state):
        saved, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(saved)
        else:
            self._inner.acquire()
        # Re-entering after a wait is a genuine acquisition: record the
        # edge against whatever the thread still holds — but never raise
        # here: Condition.wait() must return with the lock held or its
        # internal state corrupts.  The cycle still lands in
        # ``violations()`` and will raise at the next normal-path hit.
        st = _stack()
        if st and st[-1][0] != self.name:
            _record_edge(st[-1][0], self.name, raise_on_cycle=False)
        st.append([self.name, time.monotonic(), max(1, depth), id(self)])

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# ---------------------------------------------------------------------------
# Factories — the only public construction surface.


def diag_lock(name: Optional[str] = None) -> "threading.Lock | DiagLock":
    """A ``threading.Lock``, wrapped by the witness when armed."""
    if not _armed():
        return threading.Lock()
    return DiagLock(threading.Lock(), name or f"lock@{_site()}")


def diag_rlock(name: Optional[str] = None) -> "threading.RLock | DiagRLock":
    """A ``threading.RLock``, wrapped by the witness when armed."""
    if not _armed():
        return threading.RLock()
    return DiagRLock(threading.RLock(), name or f"rlock@{_site()}")


def diag_condition(lock=None, name: Optional[str] = None) -> threading.Condition:
    """A ``threading.Condition``.  When armed, its underlying lock is a
    :class:`DiagRLock` (or the caller's already-wrapped diag lock), so
    ``with cond: ... cond.wait()`` keeps exact held-set bookkeeping —
    the wait's full release/re-acquire goes through the wrapper's
    ``_release_save``/``_acquire_restore``."""
    if not _armed():
        return threading.Condition(lock)
    if lock is None:
        lock = DiagRLock(threading.RLock(), name or f"cond@{_site()}")
    elif not isinstance(lock, _DiagBase):
        lock = DiagRLock(lock, name or f"cond@{_site()}")
    return threading.Condition(lock)
