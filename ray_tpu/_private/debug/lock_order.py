"""Runtime lock-order witness — the dynamic half of graftcheck.

Parity: the reference runs plasma/raylet under TSan (SURVEY §5.2), whose
deadlock detector reports *potential* lock-order inversions from a single
run, not just ones that actually deadlocked.  Python has no TSan, so this
module wraps ``threading.Lock/RLock/Condition`` behind factory functions
(:func:`diag_lock` / :func:`diag_rlock` / :func:`diag_condition`) that are
zero-cost pass-throughs returning the plain ``threading`` primitive unless
``RAY_TPU_LOCK_DIAG=1`` is set at creation time.

Armed, every acquisition is recorded against a **name-level** global
acquisition graph (all instances created at one call site share a node, so
an ABBA order between two *differently named* locks is caught regardless
of which instances were involved — exactly the shape of the PR-6
store-lock -> refcount-lock deadlock).  Reentrancy is tracked by lock
*instance*: re-acquiring the same object bumps a depth counter, while
nesting two different instances of the same name (hierarchical
same-class locking, deadlock-free only under a global instance order
the name-level graph cannot see) is recorded as a self-edge,
observable via :func:`same_name_nestings` but never raised on — the
static analyzer's R1 self-edge check covers the non-reentrant case.
The witness raises
:class:`LockOrderViolation` the moment an edge closes a cycle, and
:class:`LockHoldBudgetExceeded` when a lock is held longer than
``RAY_TPU_LOCK_HOLD_BUDGET_S`` (0 = unlimited, the default: tier-1 boxes
can stall multi-second under sanitizer compiles, so the budget is an
opt-in probe, not an always-on gate).

The tier-1 conftest arms the witness for the whole suite, so every
existing test doubles as a lock-order probe.

Cost when armed: the steady-state acquire path is thread-local list ops
plus one dict read (edge dedup); the internal registry lock is taken only
when a *new* edge is inserted, which happens a bounded number of times
per process (#locks is small and fixed).

Striped-lock naming contract: a lock that is one stripe of a sharded
hot-path structure is named ``<Base>[sNN]`` (two-digit stripe index,
e.g. ``TaskEventBuffer._lock[s03]``, ``ReferenceCounter._lock[s12]``).
Witness edges and contention histograms stay per-stripe — a stripe-order
inversion or one hot stripe is visible as itself — while
``debug.report.striped_lock_rollup()`` re-aggregates the suffix back to
the base name so post-striping waits compare 1:1 against pre-striping
baselines.  Keep the suffix exactly ``[s`` + digits + ``]`` and at the
END of the name; the rollup matches on that.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the global acquisition graph."""


class LockHoldBudgetExceeded(RuntimeError):
    """A lock was held longer than the configured hold budget."""


def _armed() -> bool:
    return os.environ.get("RAY_TPU_LOCK_DIAG", "") == "1"


def _contention_armed() -> bool:
    """Contention profiling (``RAY_TPU_LOCK_CONTENTION=1``): the
    always-cheap mode — per-named-lock sampled acquire-wait and
    hold-time histograms, plus live held-lock sets for wedge reports,
    WITHOUT the witness's acquisition-graph cycle checks.  Arms at lock
    creation time, like the witness."""
    return os.environ.get("RAY_TPU_LOCK_CONTENTION", "") == "1"


# One-entry memo for the hold budget: releases are a hot path, so the
# float parse runs only when the env string actually changes (tests
# monkeypatch it; production sets it once).
_budget_memo: Tuple[Optional[str], float] = (None, -1.0)


def _hold_budget_s() -> float:
    global _budget_memo
    raw = os.environ.get("RAY_TPU_LOCK_HOLD_BUDGET_S", "0")
    memo_raw, memo_val = _budget_memo
    if raw == memo_raw:
        return memo_val
    try:
        val = float(raw)
    except ValueError:
        val = 0.0
    _budget_memo = (raw, val)
    return val


# ---------------------------------------------------------------------------
# Global acquisition graph (name-level).
#
# _edges maps (held_name, acquired_name) -> short provenance string for the
# first time the edge was observed.  Reads are plain dict lookups (GIL-safe,
# no lock); inserts take _graph_lock and run the cycle check.
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], str] = {}
_succ: Dict[str, List[str]] = {}
#: Cycles reported so far (kept after raise so the conftest / a test
#: harness can assert "no cycle reports" over a whole run).
_violations: List[str] = []

_tls = threading.local()

#: thread ident -> that thread's live held-lock stack (the SAME list
#: object the thread mutates, so reads see current state).  Written
#: once per thread; read by the watchdog's wedge reports.  Dead
#: threads' idents are pruned by readers against live idents.
_stacks_lock = threading.Lock()
_all_stacks: Dict[int, list] = {}


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
        with _stacks_lock:
            _all_stacks[threading.get_ident()] = st
    return st


def held_locks_by_thread() -> Dict[int, List[tuple]]:
    """Live held-lock sets: thread ident -> [(lock_name, held_for_s,
    depth), ...] outermost first.  Diagnostic snapshot — entries are
    read racily against the owning threads (fine for a wedge report;
    a torn row is at worst one stale lock line)."""
    import sys
    live = set(sys._current_frames())
    now = time.monotonic()
    out: Dict[int, List[tuple]] = {}
    with _stacks_lock:
        items = [(ident, st) for ident, st in _all_stacks.items()
                 if ident in live]
        for ident in list(_all_stacks):
            if ident not in live:
                del _all_stacks[ident]
    for ident, st in items:
        rows = []
        for entry in list(st):
            try:
                rows.append((entry[0], now - entry[1], entry[2]))
            except Exception:
                continue
        if rows:
            out[ident] = rows
    return out


# ---------------------------------------------------------------------------
# Contention profiling: per-named-lock sampled wait/hold histograms.
# Bounded by construction (#named locks is small and fixed; the
# histograms are fixed-bucket accumulators).

#: Histogram bucket bounds (seconds) for acquire-wait and hold times.
CONTENTION_BUCKETS = (1e-5, 1e-4, 1e-3, 0.01, 0.1, 1.0)


class _LockContention:
    __slots__ = ("acquires", "contended", "wait_counts", "wait_sum",
                 "wait_max", "hold_counts", "hold_sum", "hold_max",
                 "holds")

    def __init__(self):
        self.acquires = 0
        self.contended = 0      # waits that exceeded the first bucket
        self.wait_counts = [0] * (len(CONTENTION_BUCKETS) + 1)
        self.wait_sum = 0.0
        self.wait_max = 0.0
        self.holds = 0
        self.hold_counts = [0] * (len(CONTENTION_BUCKETS) + 1)
        self.hold_sum = 0.0
        self.hold_max = 0.0


def _bucket_index(value: float) -> int:
    for i, b in enumerate(CONTENTION_BUCKETS):
        if value <= b:
            return i
    return len(CONTENTION_BUCKETS)


_contention_lock = threading.Lock()
_contention: Dict[str, _LockContention] = {}

# Sample 1-in-N acquires (default every acquire: two perf_counter
# calls; the knob exists for pathological hot locks).
try:
    _SAMPLE_N = max(1, int(os.environ.get("RAY_TPU_LOCK_SAMPLE_N", "1")))
except ValueError:
    _SAMPLE_N = 1


def _contention_stats(name: str) -> _LockContention:
    st = _contention.get(name)
    if st is None:
        with _contention_lock:
            st = _contention.setdefault(name, _LockContention())
    return st


# The per-operation stat updates below run WITHOUT the registry lock,
# deliberately: this is the "always-cheap" mode and a process-global
# lock taken on every armed acquire AND release would itself be a
# convoy point — one the profiler could never attribute (its own lock
# is bare).  Under the GIL each individual += / compare is close
# enough to atomic that a rare lost increment is noise in a sampled
# diagnostic; _contention_lock guards only dict insertion and
# snapshot copies.


def _note_wait(name: str, wait_s: float) -> None:
    st = _contention_stats(name)
    st.acquires += 1
    st.wait_counts[_bucket_index(wait_s)] += 1
    st.wait_sum += wait_s
    if wait_s > st.wait_max:
        st.wait_max = wait_s
    if wait_s > CONTENTION_BUCKETS[0]:
        st.contended += 1


def _note_hold(name: str, hold_s: float) -> None:
    st = _contention_stats(name)
    st.holds += 1
    st.hold_counts[_bucket_index(hold_s)] += 1
    st.hold_sum += hold_s
    if hold_s > st.hold_max:
        st.hold_max = hold_s


def contention_snapshot() -> Dict[str, dict]:
    """Per-named-lock contention stats: acquire counts, contended
    counts, wait/hold histogram counts (``CONTENTION_BUCKETS`` + +Inf),
    sums and maxima.  Empty unless contention (or witness) mode armed
    locks have been exercised."""
    with _contention_lock:
        items = list(_contention.items())
    return {name: {
        "acquires": st.acquires,
        "contended": st.contended,
        "wait_counts": list(st.wait_counts),
        "wait_sum_s": st.wait_sum,
        "wait_max_s": st.wait_max,
        "holds": st.holds,
        "hold_counts": list(st.hold_counts),
        "hold_sum_s": st.hold_sum,
        "hold_max_s": st.hold_max,
    } for name, st in items}


def reset_contention() -> None:
    with _contention_lock:
        _contention.clear()


_sample_tick = 0


def _sampled() -> bool:
    """1-in-``RAY_TPU_LOCK_SAMPLE_N`` acquire-wait sampling gate
    (default: every acquire).  The counter bump is racy under threads —
    harmless: sampling only needs to be approximately 1-in-N."""
    if _SAMPLE_N == 1:
        return True
    global _sample_tick
    _sample_tick += 1
    return _sample_tick % _SAMPLE_N == 0


_fi_hook = None


def _fault_hook():
    """Lazily-bound ``fault_injection.hook`` (imported on first armed
    acquire: fault_injection imports ray_tpu.exceptions, which must not
    be pulled in while this module bootstraps the debug package)."""
    global _fi_hook
    if _fi_hook is None:
        try:
            from ray_tpu._private import fault_injection
            _fi_hook = fault_injection.hook
        except Exception:
            _fi_hook = False
    return _fi_hook or None


def _site(skip: int = 2) -> str:
    """file:line of the acquiring frame, skipping witness internals AND
    threading.py (a `with cond:` acquires via Condition.__enter__, whose
    frame says nothing about the caller)."""
    for fs in reversed(traceback.extract_stack(limit=skip + 8)[:-skip]):
        fn = fs.filename.replace(os.sep, "/")
        if "debug/lock_order" in fn or fn.endswith("/threading.py"):
            continue
        return f"{os.path.basename(fs.filename)}:{fs.lineno}"
    return "?"


def _stack_summary(depth: int = 12) -> str:
    """Compact call-path provenance for a NEW edge (bounded: edges are
    recorded once per (held, acquired) pair, so the cost is one-time)."""
    frames = []
    for fs in traceback.extract_stack(limit=depth + 4)[:-2]:
        fn = fs.filename.replace(os.sep, "/")
        if "debug/lock_order" in fn or fn.endswith("/threading.py"):
            continue
        frames.append(
            f"{os.path.basename(fs.filename)}:{fs.lineno}:{fs.name}")
    return " <- ".join(reversed(frames[-depth:]))


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _succ; returns a node path src..dst or None."""
    seen = {src}
    path = [src]

    def walk(node: str) -> bool:
        for nxt in _succ.get(node, ()):
            if nxt == dst:
                path.append(nxt)
                return True
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if walk(nxt):
                return True
            path.pop()
        return False

    return path if walk(src) else None


def _record_edge(held: str, acquired: str,
                 raise_on_cycle: bool = True) -> None:
    key = (held, acquired)
    if key in _edges:          # steady-state fast path: no lock
        return
    site = _stack_summary()
    with _graph_lock:
        if key in _edges:
            return
        # Adding held->acquired closes a cycle iff acquired already
        # reaches held.
        back = _find_path(acquired, held)
        _edges[key] = site
        _succ.setdefault(held, []).append(acquired)
        if back is None:
            return
        cycle = back + [acquired]
        legs = []
        for a, b in zip(cycle, cycle[1:]):
            legs.append(f"  {a} -> {b}  (first seen at "
                        f"{_edges.get((a, b), site)})")
        msg = ("lock-order cycle formed: "
               + " -> ".join(cycle) + "\n" + "\n".join(legs)
               + f"\n  closing edge {held} -> {acquired} acquired at {site}")
        _violations.append(msg)
    if raise_on_cycle:
        raise LockOrderViolation(msg)


#: name -> count of cross-instance same-name nestings observed.
_same_name: Dict[str, int] = {}


def _note_same_name_nesting(name: str) -> None:
    with _graph_lock:
        _same_name[name] = _same_name.get(name, 0) + 1


def same_name_nestings() -> Dict[str, int]:
    """Locks whose instances were nested inside each other (per name).
    Not a violation by itself — safe under a global instance order —
    but the place to look first when a same-class deadlock is
    suspected."""
    with _graph_lock:
        return dict(_same_name)


def violations() -> List[str]:
    """Cycle reports recorded so far (for harness-level assertions)."""
    with _graph_lock:
        return list(_violations)


def reset() -> None:
    """Clear the global graph and reports (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _succ.clear()
        _violations.clear()
        _same_name.clear()


def snapshot() -> tuple:
    """Copy of the global graph state — pair with :func:`restore` so a
    test that deliberately forms a cycle doesn't leave the report (or
    its edges) behind for the rest of the suite."""
    with _graph_lock:
        return (dict(_edges), {k: list(v) for k, v in _succ.items()},
                list(_violations), dict(_same_name))


def restore(state: tuple) -> None:
    edges, succ, violations, same_name = state
    with _graph_lock:
        _edges.clear()
        _edges.update(edges)
        _succ.clear()
        _succ.update({k: list(v) for k, v in succ.items()})
        _violations.clear()
        _violations.extend(violations)
        _same_name.clear()
        _same_name.update(same_name)


def graph_edges() -> Dict[Tuple[str, str], str]:
    with _graph_lock:
        return dict(_edges)


# ---------------------------------------------------------------------------
# Wrappers.


class _DiagBase:
    """Shared acquire/release bookkeeping over an inner threading lock.

    Reentrancy is tracked per-thread by lock INSTANCE: only the
    outermost acquisition of an instance records an edge / stack entry,
    so RLock recursion adds no self-edges, nesting two instances of the
    same name is still observed (``same_name_nestings``), and
    plain-Lock self-deadlocks hang exactly as they would unwrapped
    (the witness never *masks* behavior).
    """

    __slots__ = ("_inner", "name", "_witness", "_contend")

    def __init__(self, inner, name: str, witness: bool = True,
                 contend: bool = False):
        self._inner = inner
        self.name = name
        self._witness = witness
        self._contend = contend

    # -- bookkeeping ----------------------------------------------------
    # Stack entries: [name, t_acquired, depth, lock_instance_id].
    def _note_acquired(self, raise_on_cycle: bool = True) -> None:
        st = _stack()
        me = id(self)
        for entry in st:
            if entry[3] == me:
                entry[2] += 1          # true reentrancy: same instance
                return
        if st and self._witness:
            if st[-1][0] == self.name:
                # A DIFFERENT instance of the same name while one is
                # held: hierarchical same-class nesting.  Recorded as a
                # self-edge diagnostic (same_name_nestings), never
                # raised — name-level ordering cannot validate the
                # instance order that makes it safe or not.
                _note_same_name_nesting(self.name)
            else:
                _record_edge(st[-1][0], self.name,
                             raise_on_cycle=raise_on_cycle)
        st.append([self.name, time.monotonic(), 1, me])

    def _note_released(self) -> None:
        st = _stack()
        me = id(self)
        for i in range(len(st) - 1, -1, -1):
            if st[i][3] == me:
                st[i][2] -= 1
                if st[i][2] == 0:
                    held_for = time.monotonic() - st[i][1]
                    del st[i]
                    if self._contend:
                        _note_hold(self.name, held_for)
                    budget = _hold_budget_s()
                    if budget > 0 and held_for > budget:
                        raise LockHoldBudgetExceeded(
                            f"{self.name} held {held_for:.3f}s "
                            f"(budget {budget:.3f}s), released at "
                            f"{_site()}")
                return

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._contend and _sampled():
            t0 = time.perf_counter()
            got = self._inner.acquire(blocking, timeout)
            if got:
                _note_wait(self.name, time.perf_counter() - t0)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except LockOrderViolation:
                # Don't strand the inner lock: the caller's `with` body
                # never runs, so nothing would ever release it.
                self._inner.release()
                raise
            # Fault point ``lock.hold``: delay mode extends THIS
            # acquisition's hold window — the deterministic way to
            # manufacture attributable contention in tests.  An
            # error/kill-mode arming raises OUT of acquire(): the
            # caller's `with` body never runs, so the inner lock and
            # the held-set bookkeeping must be unwound here (same
            # discipline as the LockOrderViolation branch above) or
            # the lock leaks held forever.
            hook = _fault_hook()
            if hook is not None:
                try:
                    hook("lock.hold")
                except BaseException:
                    self._inner.release()
                    self._note_released()
                    raise
        return got

    def release(self) -> None:
        self._inner.release()
        self._note_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        try:
            self.release()
        except LockHoldBudgetExceeded:
            # Never mask an in-flight exception from the with-body with
            # the diagnostic — the original error is what the user is
            # debugging; the budget report rides _violations-style logs
            # only when it would otherwise be the sole signal.
            if exc and exc[0] is not None:
                return False
            raise
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self):
        return f"<{type(self).__name__} {self.name} of {self._inner!r}>"


class DiagLock(_DiagBase):
    __slots__ = ()


class DiagRLock(_DiagBase):
    """Adds the private Condition integration hooks so a
    ``threading.Condition`` built over this wrapper keeps bookkeeping
    exact across ``wait()`` (which releases all recursion levels and
    re-acquires them)."""

    __slots__ = ()

    def _release_save(self):
        st = _stack()
        me = id(self)
        depth = 0
        for i in range(len(st) - 1, -1, -1):
            if st[i][3] == me:
                depth = st[i][2]
                if self._contend:
                    _note_hold(self.name, time.monotonic() - st[i][1])
                del st[i]
                break
        saved = (self._inner._release_save()
                 if hasattr(self._inner, "_release_save")
                 else self._inner.release())
        return (saved, depth)

    def _acquire_restore(self, state):
        saved, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(saved)
        else:
            self._inner.acquire()
        # Re-entering after a wait is a genuine acquisition: record the
        # edge against whatever the thread still holds — but never raise
        # here: Condition.wait() must return with the lock held or its
        # internal state corrupts.  The cycle still lands in
        # ``violations()`` and will raise at the next normal-path hit.
        st = _stack()
        if self._witness and st and st[-1][0] != self.name:
            _record_edge(st[-1][0], self.name, raise_on_cycle=False)
        st.append([self.name, time.monotonic(), max(1, depth), id(self)])

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


# ---------------------------------------------------------------------------
# Factories — the only public construction surface.


def diag_lock(name: Optional[str] = None) -> "threading.Lock | DiagLock":
    """A ``threading.Lock``, wrapped when the witness OR contention
    profiling is armed (plain primitive otherwise)."""
    witness, contend = _armed(), _contention_armed()
    if not witness and not contend:
        return threading.Lock()
    return DiagLock(threading.Lock(), name or f"lock@{_site()}",
                    witness=witness, contend=contend)


def diag_rlock(name: Optional[str] = None) -> "threading.RLock | DiagRLock":
    """A ``threading.RLock``, wrapped when the witness OR contention
    profiling is armed."""
    witness, contend = _armed(), _contention_armed()
    if not witness and not contend:
        return threading.RLock()
    return DiagRLock(threading.RLock(), name or f"rlock@{_site()}",
                     witness=witness, contend=contend)


def diag_condition(lock=None, name: Optional[str] = None) -> threading.Condition:
    """A ``threading.Condition``.  When armed, its underlying lock is a
    :class:`DiagRLock` (or the caller's already-wrapped diag lock), so
    ``with cond: ... cond.wait()`` keeps exact held-set bookkeeping —
    the wait's full release/re-acquire goes through the wrapper's
    ``_release_save``/``_acquire_restore``."""
    witness, contend = _armed(), _contention_armed()
    if not witness and not contend:
        return threading.Condition(lock)
    if lock is None:
        lock = DiagRLock(threading.RLock(), name or f"cond@{_site()}",
                         witness=witness, contend=contend)
    elif not isinstance(lock, _DiagBase):
        lock = DiagRLock(lock, name or f"cond@{_site()}",
                         witness=witness, contend=contend)
    return threading.Condition(lock)
