"""Per-process stall watchdog over event loops and pump threads.

Every :class:`~ray_tpu._private.event_loop.EventLoop` (and long-lived
pump thread — spill io, task-event flusher) registers a
:class:`LoopBeat` and stamps it around each unit of work.  One daemon
watchdog thread per process polls the beats: a loop whose current
handler has been running past the stall budget
(``loop_stall_budget_s``), or that has queued work but made no progress
for the budget, is WEDGED — the watchdog builds a wedge report (every
thread's stack via ``sys._current_frames``, each thread's held
diag-lock set, the flight-recorder tail, swallowed-exception counts),
writes it to a crash file under ``<temp_dir>/wedges/`` and hands it to
registered listeners (node_host ships it to the head, which downgrades
the node's internal-loop liveness).  Recovery is reported too — the
report list keeps the evidence.

Parity: the reference raylet's ``DumpDebugState`` + the
``RAY_event_stats`` deadline detector ("handler X ran for Ys") — made
an active detector instead of a post-hoc log line, because PR 6/7's
hardest bugs (wedged loops, lock convoys) were only root-caused with
ad-hoc thread dumps.

The watchdog only ever REPORTS — it never kills, unwinds, or releases
anything; an over-budget handler that eventually finishes shows up as
wedge + recovery, which is exactly the evidence a tail-latency hunt
needs.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ray_tpu._private.debug import flight_recorder, lock_order, swallow

_MAX_REPORTS = 32


class LoopBeat:
    """One monitored loop/pump thread's heartbeat cell.  The stamping
    methods are the hot path (called around every handler): plain
    attribute writes + one ``time.monotonic`` — no locks."""

    __slots__ = ("name", "kind", "thread_ident", "last_beat",
                 "busy_since", "handler", "wedged", "wedge_count",
                 "_queue_depth_fn", "_stats_fn")

    def __init__(self, name: str, kind: str,
                 queue_depth: Optional[Callable[[], int]] = None,
                 stats: Optional[Callable[[], dict]] = None):
        self.name = name
        self.kind = kind
        self.thread_ident: Optional[int] = None
        self.last_beat = time.monotonic()
        self.busy_since: Optional[float] = None
        self.handler: Optional[str] = None
        self.wedged = False
        self.wedge_count = 0
        self._queue_depth_fn = queue_depth
        self._stats_fn = stats

    # -- stamping (hot path) --------------------------------------------
    def begin(self, handler: str) -> None:
        """A unit of work starts on the owning thread."""
        if self.thread_ident is None:
            self.thread_ident = threading.get_ident()
        self.handler = handler
        self.busy_since = time.monotonic()

    def end(self) -> None:
        """The unit of work finished: progress."""
        self.last_beat = time.monotonic()
        self.busy_since = None
        self.handler = None

    def alive(self) -> None:
        """Idle-loop heartbeat (pump threads stamp this each wakeup)."""
        self.last_beat = time.monotonic()

    # -- inspection ------------------------------------------------------
    def queue_depth(self) -> int:
        fn = self._queue_depth_fn
        if fn is None:
            return 0
        try:
            return int(fn())
        except Exception:
            return 0

    def stats(self) -> dict:
        fn = self._stats_fn
        if fn is None:
            return {}
        try:
            return dict(fn())
        except Exception:
            return {}

    def snapshot(self) -> dict:
        now = time.monotonic()
        busy = self.busy_since
        return {
            "name": self.name,
            "kind": self.kind,
            "busy_for_s": round(now - busy, 4) if busy else 0.0,
            "idle_for_s": 0.0 if busy else round(now - self.last_beat, 4),
            "handler": self.handler,
            "queue_depth": self.queue_depth(),
            "wedged": self.wedged,
            "wedge_count": self.wedge_count,
            **self.stats(),
        }


_lock = threading.Lock()        # debug-plane internal; exempt from R8
_beats: List[LoopBeat] = []
_listeners: List[Callable] = []
_reports: List[dict] = []
_wedges_total = 0
_crash_files_dropped = 0
_thread: Optional[threading.Thread] = None
_COLLECTOR_OWNER = None         # keeps the introspection collector alive


def _config():
    try:
        from ray_tpu._private.config import get_config
        return get_config()
    except Exception:
        return None


def _enabled() -> bool:
    cfg = _config()
    return True if cfg is None else bool(cfg.watchdog_enabled)


def stall_budget_s() -> float:
    cfg = _config()
    return 10.0 if cfg is None else float(cfg.loop_stall_budget_s)


def register(name: str, kind: str = "loop",
             queue_depth: Optional[Callable[[], int]] = None,
             stats: Optional[Callable[[], dict]] = None) -> LoopBeat:
    """Register a loop/pump thread for monitoring; starts the watchdog
    thread (and the /metrics introspection collector) on first use."""
    beat = LoopBeat(name, kind, queue_depth=queue_depth, stats=stats)
    with _lock:
        _beats.append(beat)
    _ensure_started()
    return beat


def unregister(beat: LoopBeat) -> None:
    with _lock:
        try:
            _beats.remove(beat)
        except ValueError:
            pass


def add_listener(fn: Callable[[str, dict], None]) -> None:
    """``fn(event, report)`` with event "wedge" | "recovered".  Called
    from the watchdog thread; must not block."""
    with _lock:
        _listeners.append(fn)


def remove_listener(fn: Callable) -> None:
    with _lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def wedge_reports() -> List[dict]:
    with _lock:
        return list(_reports)


def reset_reports() -> None:
    """Clear wedge evidence (tests that wedge deliberately)."""
    global _wedges_total
    with _lock:
        _reports.clear()
        _wedges_total = 0
        for b in _beats:
            b.wedged = False


def loops_snapshot() -> List[dict]:
    with _lock:
        beats = list(_beats)
    return [b.snapshot() for b in beats]


# ---------------------------------------------------------------------------
# Wedge evidence assembly.


def thread_stacks() -> Dict[str, List[str]]:
    """Every live thread's current stack, keyed ``name(ident)``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, '?')}({ident})"
        out[label] = [ln.rstrip() for ln in
                      traceback.format_stack(frame)][-24:]
    return out


def held_locks() -> Dict[str, List[str]]:
    """Per-thread held diag-lock sets (needs the witness or contention
    mode armed; empty otherwise), keyed like :func:`thread_stacks`."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, rows in lock_order.held_locks_by_thread().items():
        label = f"{names.get(ident, '?')}({ident})"
        out[label] = [f"{name} held {held_for:.3f}s (depth {depth})"
                      for name, held_for, depth in rows]
    return out


def _build_wedge_report(beat: LoopBeat, stalled_for: float) -> dict:
    return {
        "type": "wedge",
        "pid": os.getpid(),
        "ts": time.time(),
        "loop": beat.name,
        "kind": beat.kind,
        "handler": beat.handler,
        "stalled_for_s": round(stalled_for, 3),
        "budget_s": stall_budget_s(),
        "queue_depth": beat.queue_depth(),
        "stacks": thread_stacks(),
        "held_locks": held_locks(),
        "recorder_tail": flight_recorder.tail(50),
        "recorder_stats": flight_recorder.stats(),
        "swallowed": swallow.counts(),
    }


def _crash_dir() -> str:
    cfg = _config()
    base = cfg.temp_dir if cfg is not None else "/tmp/ray_tpu"
    return os.path.join(base, "wedges")


def _write_crash_file(report: dict) -> Optional[str]:
    """Persist the wedge report to disk AT TRIP TIME — if the wedged
    process is subsequently SIGKILLed, the evidence survives it."""
    try:
        d = _crash_dir()
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in report["loop"])
        path = os.path.join(
            d, f"wedge-{report['pid']}-{safe}-{int(report['ts'])}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, default=str)
        os.replace(tmp, path)
        _prune_crash_files(d, report["pid"])
        return path
    except Exception as e:
        swallow.noted("watchdog.crash_file", e)
        return None


def _prune_crash_files(d: str, pid) -> None:
    """Keep only the newest ``wedge_files_keep`` crash files THIS
    process wrote (64 hosts under a chaos schedule otherwise grow the
    wedge directory without bound).  Dropped files are counted — loss
    of evidence is explicit, task-event-buffer semantics."""
    global _crash_files_dropped
    cfg = _config()
    keep = getattr(cfg, "wedge_files_keep", 20) if cfg is not None else 20
    if keep <= 0:
        return
    prefix = f"wedge-{pid}-"
    try:
        mine = [os.path.join(d, f) for f in os.listdir(d)
                if f.startswith(prefix) and f.endswith(".json")]
    except OSError:
        return
    if len(mine) <= keep:
        return
    mine.sort(key=lambda p: os.path.getmtime(p))
    for victim in mine[:len(mine) - keep]:
        try:
            os.remove(victim)
            with _lock:
                _crash_files_dropped += 1
        except OSError as e:
            swallow.noted("watchdog.crash_prune", e)


def crash_files_dropped() -> int:
    """Crash files pruned by the per-process cap since process start."""
    with _lock:
        return _crash_files_dropped


def prune_own_crash_files() -> int:
    """Clean-shutdown hook: remove EVERY crash file this process wrote
    (the reports already shipped to the head as they fired; the disk
    copy exists for SIGKILL forensics, which a clean shutdown is not).
    Returns how many files were removed."""
    d = _crash_dir()
    prefix = f"wedge-{os.getpid()}-"
    removed = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for f in names:
        if f.startswith(prefix) and f.endswith(".json"):
            try:
                os.remove(os.path.join(d, f))
                removed += 1
            except OSError as e:
                swallow.noted("watchdog.crash_prune", e)
    return removed


def _notify(event: str, report: dict) -> None:
    with _lock:
        listeners = list(_listeners)
    for fn in listeners:
        try:
            fn(event, report)
        except Exception as e:
            swallow.noted("watchdog.listener", e)


# ---------------------------------------------------------------------------
# The watchdog thread.


def _ensure_started() -> None:
    global _thread
    if not _enabled():
        return
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _thread = threading.Thread(target=_run, daemon=True,
                                   name="ray_tpu::watchdog")
        _thread.start()
    _ensure_collector()


def _run() -> None:
    while True:
        budget = stall_budget_s()
        cfg = _config()
        poll = cfg.watchdog_poll_interval_s if cfg is not None else 0.5
        time.sleep(max(0.05, min(poll, budget / 4 if budget > 0 else poll)))
        if budget <= 0:
            continue
        try:
            _poll_once(budget)
        except Exception as e:
            swallow.noted("watchdog.poll", e)


def _poll_once(budget: float) -> None:
    global _wedges_total
    now = time.monotonic()
    with _lock:
        beats = list(_beats)
    for beat in beats:
        busy = beat.busy_since
        if busy is not None and now - busy > budget:
            stalled = now - busy
        elif busy is None and beat.queue_depth() > 0 \
                and now - beat.last_beat > budget:
            # Work queued but the loop thread is not running it: the
            # thread died, or is parked in a wait it will never leave.
            stalled = now - beat.last_beat
        else:
            if beat.wedged:
                beat.wedged = False
                _notify("recovered", {
                    "type": "recovered", "pid": os.getpid(),
                    "ts": time.time(), "loop": beat.name})
                flight_recorder.record("watchdog.recovered",
                                       loop=beat.name)
            continue
        if beat.wedged:
            continue            # one report per wedge episode
        beat.wedged = True
        beat.wedge_count += 1
        report = _build_wedge_report(beat, stalled)
        flight_recorder.record("watchdog.wedge", loop=beat.name,
                               handler=beat.handler,
                               stalled_for_s=round(stalled, 3))
        path = _write_crash_file(report)
        if path:
            report["crash_file"] = path
        with _lock:
            _reports.append(report)
            del _reports[:-_MAX_REPORTS]
            _wedges_total += 1
        _notify("wedge", report)


# ---------------------------------------------------------------------------
# /metrics: one process-wide introspection collector exporting the
# orphaned in-memory diagnostics — swallowed-exception counters, lock
# contention histograms, watchdog state.  (Per-loop handler stats are
# exported by each EventLoop's own collector.)


class _IntrospectionOwner:
    """Weakref-able anchor tying the process-wide introspection
    collector's series to this module's lifetime."""


def _ensure_collector() -> None:
    global _COLLECTOR_OWNER
    if _COLLECTOR_OWNER is not None:
        return
    try:
        from ray_tpu._private.metrics_agent import get_metrics_registry
    except Exception:
        return
    owner = _IntrospectionOwner()

    def _collect(_owner):
        _render_introspection_metrics()

    _COLLECTOR_OWNER = owner
    get_metrics_registry().register_collector(owner, _collect)


def _render_introspection_metrics() -> None:
    from ray_tpu._private.metrics_agent import (_Hist,
                                                get_metrics_registry)
    reg = get_metrics_registry()
    # Swallowed-exception counters (debug.swallow — previously only
    # visible in-process).
    reg.register("ray_tpu.swallowed_exceptions", "counter",
                 "deliberately-swallowed pump-loop exceptions per site")
    for site, n in swallow.counts().items():
        reg.put_series("ray_tpu.swallowed_exceptions",
                       (("site", site),), float(n))
    # Watchdog state.
    with _lock:
        wedged = sum(1 for b in _beats if b.wedged)
        total = _wedges_total
    reg.register("ray_tpu.watchdog.wedged_loops", "gauge",
                 "loops currently past their stall budget")
    reg.put_series("ray_tpu.watchdog.wedged_loops", (), float(wedged))
    reg.register("ray_tpu.watchdog.wedge_reports", "counter",
                 "wedge reports emitted since process start")
    reg.put_series("ray_tpu.watchdog.wedge_reports", (), float(total))
    with _lock:
        dropped = _crash_files_dropped
    reg.register("ray_tpu.watchdog.crash_files_dropped", "counter",
                 "crash files pruned by the per-process wedge cap")
    reg.put_series("ray_tpu.watchdog.crash_files_dropped", (),
                   float(dropped))
    # Lock contention histograms (sampled acquire-wait + hold time per
    # named lock; empty unless contention/witness mode armed).
    buckets = list(lock_order.CONTENTION_BUCKETS)
    snap = lock_order.contention_snapshot()
    if not snap:
        return
    reg.register("ray_tpu.lock.acquire_wait_seconds", "histogram",
                 "sampled lock acquire-wait time per named lock",
                 buckets=buckets)
    reg.register("ray_tpu.lock.hold_seconds", "histogram",
                 "lock hold time per named lock", buckets=buckets)
    reg.register("ray_tpu.lock.contended_acquires", "counter",
                 "sampled acquires that waited past the first bucket")
    for name, st in snap.items():
        labels = (("lock", name),)
        wait = _Hist(len(buckets))
        wait.counts[:] = st["wait_counts"][:len(buckets)]
        wait.sum = st["wait_sum_s"]
        wait.count = st["acquires"]
        reg.put_series("ray_tpu.lock.acquire_wait_seconds", labels, wait)
        hold = _Hist(len(buckets))
        hold.counts[:] = st["hold_counts"][:len(buckets)]
        hold.sum = st["hold_sum_s"]
        hold.count = st["holds"]
        reg.put_series("ray_tpu.lock.hold_seconds", labels, hold)
        reg.put_series("ray_tpu.lock.contended_acquires", labels,
                       float(st["contended"]))
