"""TaskSpecification + SchedulingClass interning.

Parity: reference ``src/ray/common/task/task_spec.h:197`` (TaskSpecification)
and ``:297`` (SchedulingClass interning — tasks with identical resource shape
and scheduling options share an interned integer id, which is the queueing
key of ``ClusterTaskManager`` and the dedup key that turns 1M pending tasks
into ~100s of distinct rows for the batched TPU solve, SURVEY.md §3.4).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import (
    ActorID, FunctionID, JobID, ObjectID, PlacementGroupID, TaskID, WorkerID,
)
from ray_tpu._private.debug.lock_order import diag_lock
from ray_tpu.scheduler.policy import SchedulingOptions, SchedulingType
from ray_tpu.scheduler.resources import ResourceRequest

# ---------------------------------------------------------------------------
# SchedulingClass interning (task_spec.h:297).
# ---------------------------------------------------------------------------

_sched_class_lock = diag_lock("task_spec._sched_class_lock")
_sched_class_table: Dict[Tuple, int] = {}
_sched_class_rev: Dict[int, Tuple["ResourceRequest", "SchedulingOptions"]] = {}
_sched_class_counter = itertools.count(1)


def scheduling_class_of(resources: ResourceRequest,
                        options: SchedulingOptions) -> int:
    key = (resources.key, options.scheduling_type.value,
           options.spread_threshold,
           str(options.node_affinity_node_id),
           options.node_affinity_soft)
    with _sched_class_lock:
        cls = _sched_class_table.get(key)
        if cls is None:
            cls = next(_sched_class_counter)
            _sched_class_table[key] = cls
            _sched_class_rev[cls] = (resources, options)
        return cls


def scheduling_class_descriptor(cls: int):
    with _sched_class_lock:
        return _sched_class_rev[cls]


class TaskType:
    NORMAL_TASK = "NORMAL_TASK"
    ACTOR_CREATION_TASK = "ACTOR_CREATION_TASK"
    ACTOR_TASK = "ACTOR_TASK"
    DRIVER_TASK = "DRIVER_TASK"


@dataclass
class TaskArg:
    """One task argument: either an inlined serialized value or a reference.

    Reference: args <=100KB are inlined into the spec, larger ones are put
    in plasma and passed by reference (``_raylet.pyx:1487``).
    """

    is_inline: bool
    value: Any = None              # SerializedObject when inline
    object_id: Optional[ObjectID] = None
    owner_id: Optional[WorkerID] = None


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: str
    function_id: FunctionID
    function_name: str
    args: List[TaskArg]
    num_returns: int
    resources: ResourceRequest
    scheduling_options: SchedulingOptions
    scheduling_class: int
    owner_id: WorkerID
    parent_task_id: Optional[TaskID] = None
    depth: int = 0
    max_retries: int = 0
    retry_exceptions: bool = False
    name: str = ""
    # Actor-related
    actor_id: Optional[ActorID] = None
    actor_creation: bool = False
    actor_method_name: str = ""
    max_restarts: int = 0
    max_concurrency: int = 1
    max_task_retries: int = 0
    concurrency_group: str = ""
    # Actor creation only: {group_name: max_concurrency} — methods
    # tagged with a group execute in that group's own pool
    # (concurrency_group_manager.cc parity).
    concurrency_groups: Optional[dict] = None
    # Placement group
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    capture_child_tasks: bool = False
    # Runtime env (dict: {"env_vars": ..., "pip": ..., "working_dir": ...})
    runtime_env: Optional[dict] = None
    # Refs nested inside inlined args: borrowed for the task's lifetime
    # (reference: borrower registration, reference_count.h:61).
    borrowed_ids: List[ObjectID] = field(default_factory=list)
    # Tracing context propagated submit -> execute (the reference
    # injects a ``_ray_trace_ctx`` kwarg, tracing_helper.py:157,314).
    trace_ctx: Optional[dict] = None
    # Dynamic/streaming returns
    returns_dynamic: bool = False
    # Actor creation only: resources held while the actor is alive.  The
    # reference schedules actor placement with num_cpus (default 1) but
    # releases the CPU once the actor is up unless the user set resources
    # explicitly, so idle actors don't starve the node (task_spec.h
    # GetRequiredResources vs GetRequiredPlacementResources).
    lifetime_resources: Optional[ResourceRequest] = None

    @property
    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.from_index(self.task_id, i + 1)
                for i in range(self.num_returns)]

    def arg_object_ids(self) -> List[ObjectID]:
        return [a.object_id for a in self.args if not a.is_inline]

    def is_actor_task(self) -> bool:
        return self.task_type == TaskType.ACTOR_TASK

    def is_actor_creation(self) -> bool:
        return self.task_type == TaskType.ACTOR_CREATION_TASK

    def debug_string(self) -> str:
        return (f"{self.task_type} {self.function_name} id={self.task_id} "
                f"class={self.scheduling_class} res={self.resources.to_dict()}")


def make_spec(*, job_id: JobID, owner_id: WorkerID, function_id: FunctionID,
              function_name: str, args: List[TaskArg], num_returns: int,
              resources: Dict[str, float], scheduling_strategy=None,
              parent_task_id=None, depth=0, task_type=TaskType.NORMAL_TASK,
              **kwargs) -> TaskSpec:
    req = ResourceRequest(resources)
    lifetime = kwargs.pop("lifetime_resources", None)
    if lifetime is not None and not isinstance(lifetime, ResourceRequest):
        lifetime = ResourceRequest(lifetime)
    if lifetime is not None:
        kwargs["lifetime_resources"] = lifetime
    options = options_from_strategy(scheduling_strategy)
    spec = TaskSpec(
        task_id=TaskID.from_random(),
        job_id=job_id,
        task_type=task_type,
        function_id=function_id,
        function_name=function_name,
        args=args,
        num_returns=num_returns,
        resources=req,
        scheduling_options=options,
        scheduling_class=scheduling_class_of(req, options),
        owner_id=owner_id,
        parent_task_id=parent_task_id,
        depth=depth,
        **kwargs,
    )
    return spec


def options_from_strategy(strategy) -> SchedulingOptions:
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)
    if strategy is None or strategy == "DEFAULT":
        return SchedulingOptions.hybrid()
    if strategy == "SPREAD":
        return SchedulingOptions.spread()
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        from ray_tpu._private.ids import NodeID
        nid = strategy.node_id
        if isinstance(nid, str):
            nid = NodeID.from_hex(nid)
        return SchedulingOptions.affinity(nid, soft=strategy.soft)
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        # PG scheduling resolves to node affinity on the bundle's node at
        # submission time (handled in core_worker before spec build).
        return SchedulingOptions.hybrid()
    raise ValueError(f"Unknown scheduling strategy: {strategy!r}")
