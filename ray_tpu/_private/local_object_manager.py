"""Async spill/restore IO manager — one per raylet.

Parity: reference ``src/ray/raylet/local_object_manager.{h,cc}`` — the
raylet-side spill orchestrator that batches unpinned sealed objects into
fused spill files through dedicated IO workers, frees the plasma block
once the write lands, records the ``spilled_url`` with the owner, and
restores on demand.  Here the IO worker pool collapses to one daemon
thread per raylet (spilling is disk-bound, not CPU-bound), but the
semantics match:

* **fused batches** — many small objects per spill file
  (``min_spilling_size``), each recorded as ``path?offset=&size=``;
* **copy-out outside the store lock** — victims are marked + their
  native blocks pinned under the lock (``select_spill_victims``), the
  bulk write runs unlocked, finalization publishes atomically
  (``finish_spill_batch``); a delete racing the copy wins;
* **backpressure integration** — queued create requests
  (``_ensure_capacity``) kick ``request_spill`` and are woken by each
  finalized batch;
* **zero-restore serving** — spilled objects are read back lazily on
  ``get`` and can be served to remote pulls straight from the file
  (``NodeObjectStore.open_spilled_view``), never forcing a restore.
"""

from __future__ import annotations

import os
import threading
import uuid

from ray_tpu._private import fault_injection
from ray_tpu._private.config import get_config


class LocalObjectManager:
    """io_worker-style spill thread over one :class:`NodeObjectStore`."""

    def __init__(self, store, spill_dir: str, node_label: str = ""):
        self._store = store
        self._spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self.stats = {"spill_batches": 0, "spilled_objects": 0,
                      "spilled_bytes": 0, "spill_errors": 0}
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)
        labels = {"node": node_label or "local"}

        def _collect(mgr):
            for k, v in mgr.stats.items():
                record_internal(f"ray_tpu.local_object_manager.{k}", v,
                                **labels)
        get_metrics_registry().register_collector(self, _collect)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"ray_tpu::spill::{node_label or 'local'}")
        self._thread.start()

    # ---- control --------------------------------------------------------
    def request_spill(self) -> None:
        """Hot-path kick (queued create, over-threshold put): one Event
        set, no locks, no IO."""
        self._wake.set()

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()

    # ---- the io thread --------------------------------------------------
    def _loop(self) -> None:
        from ray_tpu._private.debug import watchdog
        beat = watchdog.register(
            self._thread.name.replace("ray_tpu::", ""), kind="pump",
            queue_depth=lambda: 1 if self._wake.is_set() else 0)
        try:
            while not self._stopped.is_set():
                self._wake.wait(timeout=0.5)
                if self._stopped.is_set():
                    return
                self._wake.clear()
                beat.begin("spill")
                try:
                    while self._store.spill_shortfall() > 0:
                        if not self._spill_once():
                            break
                except Exception:
                    # The spiller must survive anything (disk full,
                    # injected faults): the store's inline path and
                    # queue deadline still bound callers.
                    self.stats["spill_errors"] += 1
                finally:
                    beat.end()
        finally:
            watchdog.unregister(beat)

    def _spill_once(self) -> bool:
        cfg = get_config()
        shortfall = self._store.spill_shortfall()
        if shortfall <= 0:
            return False
        # Fuse small objects: batch at least min_spilling_size (capped
        # to half the store — tiny test stores must not spill
        # everything in one sweep) per file.
        max_bytes = max(shortfall,
                        min(cfg.min_spilling_size,
                            self._store.capacity // 2))
        batch = self._store.select_spill_victims(max_bytes)
        if not batch:
            return False
        path = os.path.join(self._spill_dir,
                            f"batch-{uuid.uuid4().hex[:12]}")
        results = []
        offset = 0
        from ray_tpu.util import tracing
        try:
            fault_injection.hook("spill.write")
            # Spilled-object ids ride the span (bounded) so the job
            # profiler can attribute spill time to the DAG edges that
            # consumed those objects; force-recorded when armed.
            with tracing.span("object.spill", category="spill",
                              objects=len(batch),
                              force=get_config().job_profiler_enabled,
                              object_ids=[oid.hex() for oid, _e, _s
                                          in batch[:64]]), \
                    open(path, "wb") as f:
                for object_id, entry, source in batch:
                    if isinstance(source, memoryview):
                        nbytes = source.nbytes
                        f.write(source)
                    else:
                        blob = source.to_bytes()
                        nbytes = len(blob)
                        f.write(blob)
                    results.append((object_id, entry, offset, nbytes,
                                    True))
                    offset += nbytes
        except Exception:
            # Whole batch fails closed: victims are unmarked/unpinned
            # and stay in memory; the file (possibly partial) goes.
            self.stats["spill_errors"] += 1
            results = [(object_id, entry, 0, 0, False)
                       for object_id, entry, _ in batch]
            self._store.finish_spill_batch(path, results)
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        n = self._store.finish_spill_batch(path, results)
        from ray_tpu._private.debug import flight_recorder
        flight_recorder.record("spill.batch", objects=len(batch),
                               published=n, bytes=offset)
        if n == 0:
            # Every victim was deleted mid-copy: drop the orphan file.
            try:
                os.unlink(path)
            except OSError:
                pass
            return True
        self.stats["spill_batches"] += 1
        self.stats["spilled_objects"] += n
        self.stats["spilled_bytes"] += offset
        return True
