"""ObjectRef — the user-facing future/handle to a stored object.

Parity target: the reference's ``ObjectRef`` (Cython,
``python/ray/includes/object_ref.pxi``): holds the binary id + owner address,
participates in distributed refcounting via ctor/dtor hooks, supports
``future()`` interop and is awaitable.
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

from ray_tpu._private.ids import ObjectID, WorkerID


class ObjectRef:
    __slots__ = ("_id", "_owner_id", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_id: Optional[WorkerID] = None,
                 skip_adding_local_ref: bool = False):
        self._id = object_id
        self._owner_id = owner_id
        self._registered = False
        if not skip_adding_local_ref:
            wk = _current_worker()
            if wk is not None:
                wk.core_worker.reference_counter.add_local_ref(self._id)
                self._registered = True

    # -- identity ---------------------------------------------------------
    def object_id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def owner_id(self) -> Optional[WorkerID]:
        return self._owner_id

    def owner_id_binary(self):
        return self._owner_id.binary() if self._owner_id else None

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self._id == other._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Plain pickling path (outside the store serializer): keep identity,
        # do not register a local ref — the store serializer handles borrows.
        return (ObjectRef, (self._id, self._owner_id, True))

    def __copy__(self):
        # A copied handle is a real second reference (unlike the pickle
        # path): it must pin independently or its deletion under-counts.
        return ObjectRef(self._id, self._owner_id)

    def __deepcopy__(self, _memo):
        return ObjectRef(self._id, self._owner_id)

    # -- refcounting hooks ------------------------------------------------
    def __del__(self):
        # ENQUEUE-only (release_local_ref_async): a destructor fires
        # from GC at whatever allocation point interrupted the thread —
        # possibly inside a store-lock or task-manager-lock region.
        # Running the out-of-scope cascade inline there nests runtime
        # locks in arbitrary orders (the lock-order witness caught a
        # MemoryStore<->TaskManager ABBA formed exactly this way); the
        # reference counter's drain applies the release from a clean
        # context, and its query APIs settle the queue synchronously.
        if self._registered:
            try:
                wk = _current_worker()
                if wk is not None and wk.core_worker is not None:
                    wk.core_worker.reference_counter \
                        .release_local_ref_async(self._id)
            except Exception:
                pass  # interpreter teardown: module globals may be gone

    # -- future interop ---------------------------------------------------
    def future(self) -> concurrent.futures.Future:
        """A concurrent.futures.Future resolving to the object's value."""
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _cb(value, err):
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(value)

        wk = _current_worker()
        wk.core_worker.get_async(self, _cb)
        return fut

    def __await__(self):
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()


def _current_worker():
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker_or_none()
    if w is None or not w.connected:
        return None
    return w
