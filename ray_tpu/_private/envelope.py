"""Cluster-scale envelope driver (ROADMAP open item 1).

Stands up a 50–64-host fleet of REAL node_host OS processes via
``LocalProcessProvider``, then drives the full envelope — actors
created/called/destroyed in waves, placement groups across all four
strategies, 100 MiB–1 GiB objects broadcast 1→N through the PR 12
relay chains — while a seeded :mod:`chaos_schedule` keeps asymmetric
partitions, SIGKILLs, RPC delays/duplicates and spill faults firing
underneath it.

The contract is ZERO SILENT LOSS, and the driver is its own auditor:

* every actor call carries a token the reply must echo — a wrong value
  is a ``silent_loss`` row, an exception/timeout is an ATTRIBUTED
  failure row (the difference is the whole point);
* every broadcast consumer returns the sha256 of the payload it saw —
  any digest differing from the origin's is silent loss;
* every latency number comes from the PR 15 critical-path plane
  (``task_event_manager.latency_summary()``), so a cliff has a
  per-stage breakdown, not a guess.

Entry points: :func:`run_envelope` (importable — tests and
``bench_runtime.py --envelope-smoke`` call it in-process),
:func:`main` (``python -m ray_tpu._private.envelope`` /
``tools/envelope.py`` / ``ray-tpu envelope``).  Results land as a JSON
document (``ENVELOPE_r06.json`` for the recorded run).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Workload atoms (module level so they pickle into remote workers).


class _EnvelopeActor:
    """Echo actor with a tamper-evident call counter: the reply must
    carry the creation token AND the per-actor monotone sequence — a
    duplicated execution (retry that was not provably a retry) or a
    cross-wired reply shows up as a mismatch, not a pass."""

    def __init__(self, token: int):
        self.token = token
        self.calls = 0

    def echo(self, i: int):
        self.calls += 1
        return (self.token, i, self.calls)

    def total(self) -> int:
        return self.calls


def _digest_blob(blob) -> str:
    data = blob if isinstance(blob, (bytes, bytearray)) else bytes(blob)
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# Calibration.


def envelope_system_config(hosts: int,
                           overrides: Optional[dict] = None,
                           cpu_count: Optional[int] = None) -> dict:
    """System config for a many-process fleet sharing few cores: the
    heartbeat cadence relaxes with fleet size so liveness stays honest
    when 50+ daemons timeshare one box (a 100 ms beat across 64
    processes on 1 core is scheduler noise, not a liveness signal).

    When ``cpu_count`` is given and the fleet oversubscribes it ≥4×,
    a second tier kicks in: per-host thread counts and control-plane
    cadences shrink so the run-queue stays bounded.  Without it a
    50-host fleet on one core carries ~3200 dispatch threads, 10k
    event-loop wakeups/s and 100 control RPCs/s — load average in the
    four digits, and the head never gets the quantum it needs to
    ANSWER a registration (measured: stand-up dead at 420 s, load
    1191).  ``cpu_count=None`` (the default) applies only the
    fleet-size tier, so calibration stays deterministic for tests."""
    hb = 500 if hosts > 16 else 100
    cfg = {
        "raylet_heartbeat_period_milliseconds": hb,
        "num_heartbeats_suspect": 6,
        "num_heartbeats_timeout": 12,
        "gcs_resource_broadcast_period_milliseconds": max(200, hb),
        "lease_reconcile_grace_s": 2.0,
        "metrics_report_interval_ms": 1000,
    }
    oversub = hosts / max(1, cpu_count or hosts)
    if hosts > 16 and oversub >= 4:
        cfg.update({
            # 2 s beats: liveness grace (6/12 beats -> 12 s/24 s)
            # must dwarf worst-case scheduling delay, not sit inside
            # it — otherwise every GIL stall reads as a death.
            "raylet_heartbeat_period_milliseconds": 2000,
            "gcs_resource_broadcast_period_milliseconds": 2000,
            "metrics_report_interval_ms": 5000,
            # Thread-count hygiene: 8 dispatch threads/host instead
            # of 64, 50 ms ticks instead of 5 ms.
            "rpc_dispatch_pool_size": 8,
            "event_loop_tick_ms": 50,
            # The watchdog must not mistake CPU famine for a wedge.
            "loop_stall_budget_s": 60.0,
            "watchdog_poll_interval_s": 2.0,
        })
    cfg.update(overrides or {})
    return cfg


def chaos_bands(system_config: dict) -> Tuple[tuple, tuple]:
    """Partition duration bands derived from the run's OWN grace
    config: flaps land inside the suspect grace (must cause zero
    restarts — placement pause only), holds straddle the dead grace so
    some nodes get declared dead, come back talking, and are provably
    FENCED (the acceptance criterion's nonzero fence-rejection
    counters)."""
    period_s = system_config["raylet_heartbeat_period_milliseconds"] / 1e3
    suspect_s = period_s * system_config["num_heartbeats_suspect"]
    dead_s = period_s * system_config["num_heartbeats_timeout"]
    flap = (0.25 * suspect_s, 0.8 * suspect_s)
    hold = (1.05 * suspect_s, 1.5 * dead_s)
    return flap, hold


# ---------------------------------------------------------------------------
# The drive.


def run_envelope(hosts: int = 50, cpus_per_host: int = 4,
                 actors: int = 10_000, actor_wave: int = 500,
                 calls_per_actor: int = 1,
                 pgs: int = 1_000, pg_wave: int = 50,
                 broadcasts: Tuple[Tuple[int, int], ...] = ((128, 12),
                                                            (1024, 2)),
                 chaos: bool = True, chaos_seed: int = 6,
                 chaos_events: Optional[int] = None,
                 chaos_window_s: Optional[float] = None,
                 system_config: Optional[dict] = None,
                 stand_up_timeout: float = 240.0,
                 spawn_stagger_s: Optional[float] = None,
                 get_timeout_s: float = 120.0,
                 log=print) -> dict:
    """Run the envelope; returns the result document (also the JSON
    written by :func:`main`).  ``broadcasts`` is ``((size_mib,
    n_consumers), ...)``."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu._private import chaos_schedule
    from ray_tpu.autoscaler.node_provider import (
        LocalProcessProvider, TAG_NODE_KIND, TAG_NODE_TYPE,
        NODE_KIND_WORKER)

    sys_cfg = envelope_system_config(hosts, system_config,
                                     cpu_count=os.cpu_count())
    result: Dict[str, object] = {
        "round": "r06",
        "hosts": hosts,
        "cpus_per_host": cpus_per_host,
        "config": dict(sys_cfg),
        "cpu_count": os.cpu_count() or 1,
        # Honest marking: a fleet of OS processes timesharing fewer
        # cores than hosts measures the CONTROL PLANE's correctness
        # under contention, not per-host throughput.
        "cpu_throttled": (os.cpu_count() or 1) < hosts,
        "phases": {},
        "failures": [],
        "silent_loss": 0,
    }
    phases: Dict[str, dict] = result["phases"]  # type: ignore[assignment]

    t_init = time.monotonic()
    ray_tpu.init(num_cpus=cpus_per_host, _system_config=sys_cfg)
    w = global_worker()
    cluster = w.cluster

    # ---- fleet stand-up (one registration storm) -----------------------
    # On an oversubscribed box (fewer cores than hosts), pace the
    # Popen calls: 50 interpreters booting at the same instant starve
    # the head of the CPU it needs to answer registrations at all.
    # The admission gate still gets its storm — boots complete in
    # overlapping waves — but the head keeps scheduling quanta.
    if spawn_stagger_s is None:
        spawn_stagger_s = 0.25 if (os.cpu_count() or 1) < hosts else 0.0
    log(f"[envelope] standing up {hosts} node hosts "
        f"(spawn stagger {spawn_stagger_s:.2f}s) ...")
    provider = LocalProcessProvider(
        cluster, {"worker": {"resources": {"CPU": float(cpus_per_host)}}})
    handles = provider.create_node(
        {"resources": {"CPU": float(cpus_per_host)}},
        {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: "worker"},
        hosts, timeout=stand_up_timeout,
        spawn_interval_s=spawn_stagger_s)
    cluster.wait_for_nodes(hosts + 1, timeout=stand_up_timeout)
    stand_up_s = time.monotonic() - t_init
    phases["stand_up"] = {
        "wall_s": round(stand_up_s, 3),
        "hosts": hosts,
        "spawn_stagger_s": spawn_stagger_s,
        "registrations_deferred":
            cluster.head_service.registrations_deferred,
    }
    log(f"[envelope] fleet up in {stand_up_s:.1f}s "
        f"(registrations deferred: "
        f"{cluster.head_service.registrations_deferred})")

    # ---- chaos ---------------------------------------------------------
    runner = None
    schedule = []
    if chaos:
        if chaos_events is None:
            chaos_events = max(8, hosts // 2)
        if chaos_window_s is None:
            chaos_window_s = 30.0 + hosts * 0.8
        flap, hold = chaos_bands(sys_cfg)
        schedule = chaos_schedule.generate_schedule(
            chaos_seed, chaos_window_s, chaos_events, len(handles),
            flap_band=flap, hold_band=hold)
        runner = chaos_schedule.ChaosRunner(handles, schedule).start()
        log(f"[envelope] chaos armed: {len(schedule)} events over "
            f"{chaos_window_s:.0f}s (seed {chaos_seed})")

    ledger = {"actor_create_ok": 0, "actor_create_failed": 0,
              "actor_calls_ok": 0, "actor_calls_failed": 0,
              "actor_mismatches": 0, "pg_created": 0, "pg_ready": 0,
              "pg_failed": 0, "bcast_ok": 0, "bcast_failed": 0,
              "bcast_mismatches": 0}

    try:
        _drive_actor_waves(ray_tpu, actors, actor_wave, calls_per_actor,
                           get_timeout_s, ledger, result, phases, log)
        _drive_placement_groups(pgs, pg_wave, get_timeout_s, ledger,
                                result, phases, log)
        _drive_broadcasts(ray_tpu, cluster, broadcasts, get_timeout_s,
                          ledger, result, phases, log)
        if runner is not None:
            # Let the schedule finish firing (bounded): the soak's
            # evidence is events that FIRED, not events scheduled.
            deadline = time.monotonic() + (chaos_window_s or 0) + 10.0
            while runner._thread.is_alive() and \
                    time.monotonic() < deadline:
                time.sleep(0.25)
    finally:
        if runner is not None:
            runner.stop()

    # ---- evidence ------------------------------------------------------
    result["ledger"] = ledger
    result["silent_loss"] = (ledger["actor_mismatches"] +
                            ledger["bcast_mismatches"])
    result["latency"] = \
        cluster.gcs.task_event_manager.latency_summary()
    if runner is not None:
        result["chaos"] = {
            "seed": chaos_seed,
            "scheduled": len(schedule),
            "fired": runner.events_fired,
            "skipped": runner.events_skipped,
            "event_log": runner.event_log,
        }
    result["degradation"] = _collect_degradation(cluster, handles)
    result["membership"] = _membership_rollup(cluster)
    phases["total"] = {"wall_s": round(time.monotonic() - t_init, 3)}
    return result


def _drive_actor_waves(ray_tpu, actors, wave, calls_per_actor,
                       get_timeout_s, ledger, result, phases, log):
    Act = ray_tpu.remote(_EnvelopeActor)
    t0 = time.monotonic()
    created_total = 0
    while created_total < actors:
        n = min(wave, actors - created_total)
        base = created_total
        created_total += n
        live = []
        for k in range(n):
            token = base + k
            try:
                live.append((token, Act.remote(token)))
            except Exception as e:
                ledger["actor_create_failed"] += 1
                result["failures"].append(
                    {"op": "actor_create", "token": token,
                     "error": f"{type(e).__name__}: {e}"})
        refs = []
        for token, a in live:
            per = []
            for c in range(calls_per_actor):
                try:
                    per.append((c + 1, a.echo.remote(token + c)))
                except Exception as e:
                    ledger["actor_calls_failed"] += 1
                    result["failures"].append(
                        {"op": "actor_call", "token": token,
                         "error": f"{type(e).__name__}: {e}"})
            refs.append((token, a, per))
        for token, a, per in refs:
            ok = True
            for seq, ref in per:
                try:
                    got = ray_tpu.get(ref, timeout=get_timeout_s)
                except Exception as e:
                    ok = False
                    ledger["actor_calls_failed"] += 1
                    result["failures"].append(
                        {"op": "actor_call", "token": token,
                         "error": f"{type(e).__name__}: {e}"})
                    continue
                if got != (token, token + seq - 1, seq):
                    ledger["actor_mismatches"] += 1
                    result["failures"].append(
                        {"op": "actor_call", "token": token,
                         "error": "SILENT LOSS: value mismatch",
                         "got": repr(got)})
                else:
                    ledger["actor_calls_ok"] += 1
            if ok:
                ledger["actor_create_ok"] += 1
            try:
                ray_tpu.kill(a)
            except Exception as e:
                # Killing an actor whose node chaos already took is
                # expected; the count still lands in the swallow ledger.
                from ray_tpu._private.debug import swallow
                swallow.noted("envelope.actor_kill", e)
        if (created_total // wave) % 5 == 0:
            log(f"[envelope] actors {created_total}/{actors} "
                f"({time.monotonic() - t0:.0f}s)")
    phases["actors"] = {
        "wall_s": round(time.monotonic() - t0, 3),
        "actors": actors, "wave": wave,
        "calls_per_actor": calls_per_actor,
        "actors_per_s": round(actors / max(1e-9,
                                           time.monotonic() - t0), 1),
    }


def _drive_placement_groups(pgs, wave, get_timeout_s, ledger, result,
                            phases, log):
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    strategies = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")
    t0 = time.monotonic()
    created = 0
    while created < pgs:
        n = min(wave, pgs - created)
        batch = []
        for k in range(n):
            strategy = strategies[(created + k) % len(strategies)]
            bundles = [{"CPU": 1}] if "PACK" in strategy \
                else [{"CPU": 1}, {"CPU": 1}]
            try:
                pg = placement_group(bundles, strategy=strategy)
                batch.append((strategy, pg))
                ledger["pg_created"] += 1
            except Exception as e:
                ledger["pg_failed"] += 1
                result["failures"].append(
                    {"op": "pg_create", "strategy": strategy,
                     "error": f"{type(e).__name__}: {e}"})
        for strategy, pg in batch:
            try:
                if pg.wait(timeout_seconds=get_timeout_s):
                    ledger["pg_ready"] += 1
                else:
                    ledger["pg_failed"] += 1
                    result["failures"].append(
                        {"op": "pg_ready", "strategy": strategy,
                         "error": "timeout waiting for placement"})
            except Exception as e:
                ledger["pg_failed"] += 1
                result["failures"].append(
                    {"op": "pg_ready", "strategy": strategy,
                     "error": f"{type(e).__name__}: {e}"})
            try:
                remove_placement_group(pg)
            except Exception as e:
                result["failures"].append(
                    {"op": "pg_remove", "strategy": strategy,
                     "error": f"{type(e).__name__}: {e}"})
        created += n
        if (created // wave) % 5 == 0:
            log(f"[envelope] PGs {created}/{pgs} "
                f"({time.monotonic() - t0:.0f}s)")
    phases["placement_groups"] = {
        "wall_s": round(time.monotonic() - t0, 3),
        "pgs": pgs, "strategies": list(strategies),
        "pgs_per_s": round(pgs / max(1e-9, time.monotonic() - t0), 1),
    }


def _drive_broadcasts(ray_tpu, cluster, broadcasts, get_timeout_s,
                      ledger, result, phases, log):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    consume = ray_tpu.remote(_digest_blob)
    t0 = time.monotonic()
    rows = []
    total_bytes = 0
    for size_mib, consumers in broadcasts:
        block = os.urandom(1024 * 1024)
        data = block * size_mib
        want = hashlib.sha256(data).hexdigest()
        t1 = time.monotonic()
        ref = ray_tpu.put(data)
        del data
        # Spread consumers across ALIVE remote nodes: relay chains form
        # between them (PR 12), the origin serves O(size).
        nodes = [n for n in cluster.raylets()
                 if getattr(n, "is_remote_proxy", False)]
        refs = []
        for i in range(consumers):
            node = nodes[i % len(nodes)] if nodes else None
            opts = {}
            if node is not None:
                opts["scheduling_strategy"] = \
                    NodeAffinitySchedulingStrategy(node.node_id.hex(),
                                                   soft=True)
            refs.append(consume.options(**opts).remote(ref))
        ok = failed = mism = 0
        for r in refs:
            try:
                got = ray_tpu.get(r, timeout=get_timeout_s)
            except Exception as e:
                failed += 1
                result["failures"].append(
                    {"op": "broadcast", "size_mib": size_mib,
                     "error": f"{type(e).__name__}: {e}"})
                continue
            if got != want:
                mism += 1
                result["failures"].append(
                    {"op": "broadcast", "size_mib": size_mib,
                     "error": "SILENT LOSS: digest mismatch",
                     "got": got, "want": want})
            else:
                ok += 1
        wall = time.monotonic() - t1
        moved = size_mib * 1024 * 1024 * ok
        total_bytes += moved
        ledger["bcast_ok"] += ok
        ledger["bcast_failed"] += failed
        ledger["bcast_mismatches"] += mism
        rows.append({"size_mib": size_mib, "consumers": consumers,
                     "ok": ok, "failed": failed, "mismatches": mism,
                     "wall_s": round(wall, 3),
                     "gib_per_s": round(moved / max(1e-9, wall) / 1024**3,
                                        3)})
        log(f"[envelope] broadcast {size_mib} MiB -> {consumers}: "
            f"{ok} ok, {failed} failed in {wall:.1f}s")
        try:
            del ref
        except Exception:
            pass
    phases["broadcast"] = {
        "wall_s": round(time.monotonic() - t0, 3),
        "rows": rows,
        "total_gib": round(total_bytes / 1024**3, 3),
    }


def _collect_degradation(cluster, handles) -> dict:
    """Per-fix counters — the degradation fixes' before/after evidence
    read straight from the structures, not from the (sheddable)
    metrics plane."""
    from ray_tpu._private.debug import watchdog
    head = cluster.head_service
    coalesced = sent = 0
    for r in cluster.raylets():
        if getattr(r, "is_remote_proxy", False):
            coalesced += getattr(r, "broadcasts_coalesced", 0)
            sent += getattr(r, "broadcasts_sent", 0)
    obs = {"metrics_sheds": 0, "timeline_windows_shed": 0,
           "worker_startup_throttled": 0, "nodes_polled": 0}
    for h in handles:
        proxy = h.proxy
        if proxy is None or h.proc.poll() is not None:
            continue
        try:
            stats = proxy.client.call("observability_stats", None,
                                      timeout=5.0)
        except Exception:
            continue
        obs["nodes_polled"] += 1
        for k in ("metrics_sheds", "timeline_windows_shed",
                  "worker_startup_throttled"):
            obs[k] += int(stats.get(k, 0))
    return {
        "registration_admission": {
            "deferred": head.registrations_deferred,
        },
        "broadcast_coalescing": {
            "sent": sent, "coalesced": coalesced,
        },
        "heartbeat_shedding": obs,
        "wedge_files_dropped": watchdog.crash_files_dropped(),
    }


def _membership_rollup(cluster) -> dict:
    nm = cluster.gcs.node_manager
    fenced = {nid.hex()[:12]: dict(v)
              for nid, v in nm.fence_rejections.items() if v}
    return {
        "alive": len(nm.alive_nodes),
        "dead": len(nm.dead_nodes),
        "fence_rejections_total": sum(
            sum(v.values()) for v in nm.fence_rejections.values()),
        "fence_rejections": fenced,
    }


# ---------------------------------------------------------------------------
# CLI.


def _parse_broadcasts(specs: List[str]) -> Tuple[Tuple[int, int], ...]:
    out = []
    for s in specs:
        size, _, cons = s.partition(":")
        out.append((int(size), int(cons) if cons else 4))
    return tuple(out)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="ray-tpu envelope",
        description="Cluster-scale envelope / chaos soak driver.")
    p.add_argument("--hosts", type=int, default=50)
    p.add_argument("--cpus-per-host", type=int, default=4)
    p.add_argument("--actors", type=int, default=10_000)
    p.add_argument("--actor-wave", type=int, default=500)
    p.add_argument("--calls-per-actor", type=int, default=1)
    p.add_argument("--pgs", type=int, default=1_000)
    p.add_argument("--pg-wave", type=int, default=50)
    p.add_argument("--broadcast", action="append", default=None,
                   metavar="MIB[:CONSUMERS]",
                   help="repeatable; default 128:12 and 1024:2")
    p.add_argument("--no-chaos", action="store_true")
    p.add_argument("--chaos-seed", type=int, default=6)
    p.add_argument("--chaos-events", type=int, default=None)
    p.add_argument("--chaos-window-s", type=float, default=None)
    p.add_argument("--get-timeout-s", type=float, default=120.0)
    p.add_argument("--stand-up-timeout", type=float, default=240.0)
    p.add_argument("--spawn-stagger-s", type=float, default=None,
                   help="seconds between node-host spawns during "
                        "stand-up (default: auto — 0.25 when the box "
                        "has fewer cores than hosts, else 0)")
    p.add_argument("--config", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="system-config override on top of the "
                        "fleet-size calibration (repeatable; values "
                        "parsed as JSON, falling back to string)")
    p.add_argument("--out", default="ENVELOPE_r06.json")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    broadcasts = _parse_broadcasts(args.broadcast) \
        if args.broadcast else ((128, 12), (1024, 2))
    overrides = {}
    for kv in args.config:
        key, _, raw = kv.partition("=")
        try:
            overrides[key] = json.loads(raw)
        except ValueError:
            overrides[key] = raw
    log = (lambda *_a, **_k: None) if args.quiet \
        else (lambda *a: print(*a, file=sys.stderr, flush=True))
    import ray_tpu
    try:
        result = run_envelope(
            hosts=args.hosts, cpus_per_host=args.cpus_per_host,
            actors=args.actors, actor_wave=args.actor_wave,
            calls_per_actor=args.calls_per_actor,
            pgs=args.pgs, pg_wave=args.pg_wave,
            broadcasts=broadcasts,
            chaos=not args.no_chaos, chaos_seed=args.chaos_seed,
            chaos_events=args.chaos_events,
            chaos_window_s=args.chaos_window_s,
            system_config=overrides or None,
            get_timeout_s=args.get_timeout_s,
            stand_up_timeout=args.stand_up_timeout,
            spawn_stagger_s=args.spawn_stagger_s,
            log=log)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1, default=str)
        log(f"[envelope] wrote {args.out}")
    # One summary JSON line on stdout — the contract bench_runtime's
    # subprocess harness parses.
    summary = {
        "envelope": {
            "hosts": result["hosts"],
            "actors": result["ledger"]["actor_create_ok"],
            "pgs_ready": result["ledger"]["pg_ready"],
            "broadcast_gib":
                result["phases"]["broadcast"]["total_gib"],
            "chaos_fired": result.get("chaos", {}).get("fired", 0),
            "failures": len(result["failures"]),
            "silent_loss": result["silent_loss"],
            "cpu_throttled": result["cpu_throttled"],
            "wall_s": result["phases"]["total"]["wall_s"],
        }
    }
    print(json.dumps(summary), flush=True)
    return 0 if result["silent_loss"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
