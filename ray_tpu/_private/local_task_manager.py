"""Per-node dispatch: args -> resources -> worker binding.

Parity: reference ``src/ray/raylet/local_task_manager.h:36-57`` (the 6-step
lifecycle: queued -> waiting for args (DependencyManager) -> args pinned ->
local resources allocated at instance granularity -> WorkerPool::PopWorker
-> reply to the lease request with the bound worker + resource mapping) and
``src/ray/raylet/dependency_manager.h`` (bridges the pull manager: a queued
task's missing args are pulled to the node before dispatch).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List

from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.scheduler.resources import ResourceRequest
from ray_tpu._private.debug import diag_lock, diag_rlock


class _Waiting:
    __slots__ = ("spec", "reply", "missing", "retries")

    def __init__(self, spec, reply, missing):
        self.spec = spec
        self.reply = reply
        self.missing = missing
        self.retries = {}  # oid -> failed-pull retry count


class DependencyManager:
    """Tracks tasks waiting for argument objects to become node-local."""

    def __init__(self, raylet):
        self._raylet = raylet
        self._lock = diag_lock("DependencyManager._lock")
        self._waiting: Dict = {}  # task_id -> _Waiting

    def wait_for_args(self, spec: TaskSpec, ready_cb: Callable[[], None]):
        missing: List = []
        for oid in spec.arg_object_ids():
            if not self._raylet.object_manager.is_local_or_inline(oid):
                missing.append(oid)
        if not missing:
            ready_cb()
            return
        state = _Waiting(spec, ready_cb, set(missing))
        # Keyed by a unique token, NOT task_id: duplicate lease requests may
        # carry the same representative spec, and an overwritten wait state
        # would silently drop its lease reply (observed as a starvation
        # hang under pipelined submission).
        token = object()
        with self._lock:
            self._waiting[token] = state
        for oid in missing:
            self._raylet.object_manager.pull_async(
                oid, lambda ok, oid=oid: self._on_arg(token, oid, ok))

    _MAX_PULL_RETRIES = 3

    def _on_arg(self, token, oid, ok):
        with self._lock:
            state = self._waiting.get(token)
            if state is None:
                return
            if not ok:
                state.retries[oid] = state.retries.get(oid, 0) + 1
                retry = state.retries[oid] <= self._MAX_PULL_RETRIES
            else:
                retry = False
            if not retry:
                # Either the arg is ready, or retries are exhausted — in
                # the latter case dispatch anyway and let the executor's
                # fetch loop raise a proper ObjectLostError to the owner.
                state.missing.discard(oid)
            done = not state.missing
            if done:
                del self._waiting[token]
        if retry:
            # Failed pull (source died / object freed): ask the owner to
            # reconstruct from lineage, then re-pull after a short delay.
            core = self._raylet.core_worker
            if core is not None:
                try:
                    core.recover_object(oid)
                except Exception:
                    pass
            self._raylet.loop.schedule_after(
                0.02 * state.retries[oid],
                lambda: self._raylet.object_manager.pull_async(
                    oid, lambda ok2, oid=oid: self._on_arg(token, oid, ok2)),
                "dep.repull")
            return
        if done:
            state.reply()

    def num_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)


class LocalTaskManager:
    def __init__(self, raylet):
        self._raylet = raylet
        self._lock = diag_rlock("LocalTaskManager._lock")
        self._dispatch_queue: deque = deque()
        # Resources held by leased workers: worker_id -> ResourceRequest.
        self._allocated: Dict = {}
        # Arg objects pinned for a lease (GetAndPinArgsForExecutor
        # parity): worker_id -> [ObjectID].  Released with the lease —
        # a dispatch-time pin left forever would make every
        # arg-consumed object unspillable (spill starvation).
        self._arg_pins: Dict = {}
        self.dependency_manager = DependencyManager(raylet)

    # step 1-2: queue + wait for args
    def queue_and_schedule(self, spec: TaskSpec, reply: Callable):
        self.dependency_manager.wait_for_args(
            spec, lambda: self._on_args_ready(spec, reply))

    def _on_args_ready(self, spec: TaskSpec, reply: Callable):
        with self._lock:
            self._dispatch_queue.append((spec, reply))
        self._raylet.loop.post(self.dispatch, "local.dispatch")

    # steps 3-6: pin args, pop worker, bind.  Resources were already
    # reserved by ClusterTaskManager at scheduling-decision time (the
    # cluster view's local row is the authoritative NodeResources map),
    # so dispatch only needs a worker slot.
    def dispatch(self):
        prestart_bound = get_config().num_prestart_workers
        if prestart_bound:
            # Predictive warm-worker prestart from dispatch-queue depth
            # (PrestartWorkers parity): start the burst's workers on a
            # side thread while this loop binds the first ones, instead
            # of paying each startup inline in pop_worker.
            with self._lock:
                backlog = len(self._dispatch_queue)
            if backlog > 1:
                self._raylet.worker_pool.prestart_for_backlog(
                    backlog, prestart_bound)
        while True:
            with self._lock:
                if not self._dispatch_queue:
                    return
                spec, reply = self._dispatch_queue[0]
                worker = self._raylet.worker_pool.pop_worker(
                    runtime_env=spec.runtime_env)
                if worker is None:
                    return  # no worker slot; retried when one frees up
                self._dispatch_queue.popleft()
                held = spec.resources
                if spec.is_actor_creation() and \
                        spec.lifetime_resources is not None:
                    # Return placement-only resources (default actor CPU)
                    # to the node as soon as the actor is placed.
                    held = spec.lifetime_resources
                    placed = spec.resources.to_dict()
                    kept = held.to_dict()
                    delta = {k: v - kept.get(k, 0.0)
                             for k, v in placed.items()
                             if v - kept.get(k, 0.0) > 0}
                    if delta:
                        self._raylet.cluster_view.add_back(
                            self._raylet.node_id, ResourceRequest(delta))
                        self._raylet.cluster_task_manager.on_resources_freed()
                self._allocated[worker.worker_id] = held
                pinned = list(spec.arg_object_ids())
                self._arg_pins[worker.worker_id] = pinned
            for oid in pinned:
                self._raylet.object_store.pin(oid)
            # NOTE no SUBMITTED_TO_WORKER event here: the lease reply's
            # worker may end up running a DIFFERENT task than this
            # representative spec (transport-side queue rotation, and
            # lease reuse never comes back through here at all) — the
            # transport emits it at the actual spec->worker push.
            reply({"worker": worker, "raylet": self._raylet,
                   "resources": spec.resources})

    def release_worker_resources(self, worker) -> None:
        with self._lock:
            req = self._allocated.pop(worker.worker_id, None)
            pinned = self._arg_pins.pop(worker.worker_id, None)
        if pinned:
            for oid in pinned:
                self._raylet.object_store.unpin(oid)
        if req is not None:
            self._raylet.cluster_view.add_back(self._raylet.node_id, req)
            self._raylet.loop.post(self.dispatch, "local.dispatch")
            self._raylet.cluster_task_manager.on_resources_freed()

    def allocated_for(self, worker_id) -> ResourceRequest:
        with self._lock:
            return self._allocated.get(worker_id, ResourceRequest())

    def num_queued(self) -> int:
        with self._lock:
            return len(self._dispatch_queue)
