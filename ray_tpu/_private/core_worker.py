"""CoreWorker — the in-process runtime of the driver (and, logically, of
every executor thread).

Parity: reference ``src/ray/core_worker/core_worker.cc`` — ``Put`` (:878),
``Get`` (:1081, merging memory store + plasma + remote pull),
``SubmitTask`` (:1650), ``CreateActor`` (:1709), ``CreatePlacementGroup``
(:1869), ``SubmitActorTask`` (:1940), ``ExecuteTask`` (:2255 — lives in
executor.py here), plus the ``ObjectRecoveryManager``
(object_recovery_manager.cc: lost objects are reconstructed by resubmitting
the creating task from pinned lineage).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions
from ray_tpu._private import worker_context
from ray_tpu._private.config import get_config
from ray_tpu._private.direct_actor_submitter import DirectActorTaskSubmitter
from ray_tpu._private.direct_task_submitter import DirectTaskSubmitter
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import (
    ActorID, JobID, ObjectID, PlacementGroupID, TaskID, WorkerID)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import (
    DeviceObject, InPlasmaMarker, MemoryStore, ObjectVanishedError,
    entry_value)
from ray_tpu._private.reference_counter import ReferenceCounter
from ray_tpu._private.serialization import SerializedObject, serialize
from ray_tpu._private.task_manager import TaskManager
from ray_tpu._private.task_spec import TaskArg, TaskSpec
from ray_tpu._private.debug import diag_lock


class CoreWorker:
    def __init__(self, cluster, job_id: JobID, is_driver: bool = True):
        self.cluster = cluster
        self.job_id = job_id
        self.worker_id = WorkerID.from_random()
        self.is_driver = is_driver
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter()
        self.task_manager = TaskManager(self)
        self.function_manager = FunctionManager(cluster.gcs.kv)
        self.task_submitter = DirectTaskSubmitter(self)
        self.actor_submitter = DirectActorTaskSubmitter(self)
        self.driver_task_id = TaskID.for_driver(job_id)
        self._put_counter = 0
        self._put_lock = diag_lock("CoreWorker._put_lock")
        self.metrics: Dict[str, float] = {"tasks_finished": 0,
                                          "task_exec_seconds": 0.0,
                                          "tasks_submitted": 0,
                                          "actor_tasks_submitted": 0,
                                          "lineage_reconstructions": 0}
        # Per-creating-task reconstruction state (attempt count +
        # exponential-backoff gate) — object_recovery_manager parity.
        self._recon_lock = diag_lock("CoreWorker._recon_lock")
        self._reconstructions: Dict[TaskID, _ReconState] = {}
        # Exported at scrape time (/metrics): the hot path only bumps
        # these plain counters.
        from ray_tpu._private.metrics_agent import (get_metrics_registry,
                                                    record_internal)

        wlabel = {"worker": self.worker_id.hex()[:8]}

        def _collect(cw):
            for k, v in cw.metrics.items():
                record_internal(f"ray_tpu.core_worker.{k}", v, **wlabel)
            record_internal("ray_tpu.core_worker.objects_in_memory_store",
                            len(cw.memory_store._entries), **wlabel)
            # Promoted to a top-level name: the recovery dashboards and
            # chaos tests key on ray_tpu_lineage_reconstructions.
            record_internal("ray_tpu.lineage_reconstructions",
                            cw.metrics["lineage_reconstructions"],
                            **wlabel)
        get_metrics_registry().register_collector(self, _collect)
        # Free stored copies when objects go out of scope.
        self.reference_counter.subscribe_deleted(self._free_object)

    # ------------------------------------------------------------------
    @property
    def local_raylet(self):
        ctx = worker_context.get_context()
        if ctx.node is not None:
            return ctx.node
        return self.cluster.head_node

    def _next_put_id(self) -> ObjectID:
        ctx = worker_context.current_task_spec()
        base_task = ctx.task_id if ctx is not None else self.driver_task_id
        with self._put_lock:
            self._put_counter += 1
            # Put ids use a high index band so they never collide with
            # return ids of the same task (reference: put index counter).
            return ObjectID.from_index(base_task, 2**40 + self._put_counter)

    # ---- Put / Get / Wait (core_worker.cc:878,1081) --------------------
    def put(self, value: Any, _owner=None) -> ObjectRef:
        from ray_tpu.util import tracing
        object_id = self._next_put_id()
        with tracing.span("put", category="object",
                          object_id=object_id.hex()):
            self.put_value(object_id, value)
        return ObjectRef(object_id, owner_id=self.worker_id)

    def put_value(self, object_id: ObjectID, value: Any):
        cfg = get_config()
        if _is_device_array(value):
            # Device-resident path: keep the buffer on TPU, no host copy.
            data = DeviceObject(value)
            self.reference_counter.add_owned_object(object_id)
            raylet = self.local_raylet
            raylet.object_store.put(object_id, data)
            self.cluster.object_directory.add_location(object_id,
                                                       raylet.node_id,
                                                       size=data.nbytes)
            return
        serialized = serialize(value)
        contained = [r.object_id() for r in serialized.contained_refs]
        self.reference_counter.add_owned_object(object_id,
                                                contained_ids=contained)
        if serialized.total_bytes <= cfg.max_direct_call_object_size:
            self.memory_store.put(object_id, serialized)
        else:
            raylet = self.local_raylet
            raylet.object_store.put(object_id, serialized)
            self.cluster.object_directory.add_location(
                object_id, raylet.node_id, size=serialized.total_bytes)

    def put_return_value(self, object_id: ObjectID, value: Any, node) -> int:
        """Store a task return (small -> owner memory store 'inline reply';
        big -> executing node's store + directory)."""
        if _is_device_array(value):
            data = DeviceObject(value)
            node.object_store.put(object_id, data)
            self.cluster.object_directory.add_location(object_id,
                                                       node.node_id,
                                                       size=data.nbytes)
            return data.nbytes
        serialized = serialize(value)
        contained = [r.object_id() for r in serialized.contained_refs]
        if contained:
            self.reference_counter.add_owned_object(
                object_id, contained_ids=contained)
        self.put_serialized_return(object_id, serialized, node)
        return serialized.total_bytes

    def put_serialized_return(self, object_id: ObjectID, serialized,
                              node):
        """Owner-side landing of an already-serialized return: small
        values seal the memory store directly; big ones go to the
        executing node's store, the directory, and an InPlasmaMarker so
        owner-side gets unblock quickly."""
        if serialized.total_bytes <= \
                get_config().max_direct_call_object_size:
            self.memory_store.put(object_id, serialized)
        else:
            node.object_store.put(object_id, serialized)
            self.cluster.object_directory.add_location(
                object_id, node.node_id, size=serialized.total_bytes)
            self.memory_store.put(object_id, InPlasmaMarker(node.node_id))

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        from ray_tpu.util import tracing
        deadline = None if timeout is None else time.monotonic() + timeout
        with tracing.span("get", category="object", n=len(refs)):
            out = []
            for ref in refs:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                out.append(self._get_one(ref, remaining))
            return out

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        object_id = ref.object_id()
        deadline = None if timeout is None else time.monotonic() + timeout
        nowhere_streak = 0
        while True:
            value, found = self._try_get_local(object_id)
            if found:
                return value
            # Deadline gate sits at the TOP: every retry arm below
            # `continue`s back here, so a bottom-of-loop check would be
            # skipped exactly on the paths that loop (e.g. a recovery
            # backoff window outlasting the caller's timeout).
            if deadline is not None and time.monotonic() >= deadline:
                raise exceptions.GetTimeoutError(
                    f"Get timed out for {object_id}")
            # Not local: is it in some node's store?
            locations = self.cluster.object_directory.get_locations(object_id)
            if locations:
                node = self.local_raylet
                done = threading.Event()
                result = {}

                def cb(ok):
                    result["ok"] = ok
                    done.set()

                node.object_manager.pull_async(object_id, cb)
                done.wait(timeout=5.0)
                if result.get("ok"):
                    continue
            else:
                # Maybe it's a pending task return: wait on the memory
                # store future briefly, then re-examine.
                try:
                    entry = self.memory_store.get(object_id, timeout=0.05)
                    value = self._entry_to_value(object_id, entry)
                    return value
                except exceptions.GetTimeoutError:
                    pass
                except _Retry:
                    continue
            # Object nowhere and not pending: try lineage reconstruction.
            if not self._is_pending(object_id) and not locations:
                if self.recover_object(object_id):
                    # Resubmitted now, or a backoff window is pending.
                    # recover_object is backoff-gated internally, so it
                    # is polled EVERY pass: a reconstructed copy lost
                    # again (second node death) gets its next attempt
                    # when the window opens, instead of being abandoned
                    # by a one-shot flag and surfacing a premature
                    # ObjectLostError ~50ms later.
                    nowhere_streak = 0
                    continue
                # Unrecoverable: allow a few rechecks (a producing task
                # may seal between store reads), then surface the loss
                # instead of spinning until the deadline.
                nowhere_streak += 1
                if nowhere_streak >= 5:
                    raise self._lost_error(
                        object_id,
                        "all copies lost and lineage reconstruction "
                        "unavailable")
                time.sleep(0.01)
            else:
                nowhere_streak = 0

    def _try_get_local(self, object_id: ObjectID) -> Tuple[Any, bool]:
        entry = self.memory_store.get_entry(object_id)
        if entry is not None and entry.sealed:
            try:
                return self._entry_to_value(object_id, entry), True
            except _Retry:
                return None, False
        raylet = self.local_raylet
        if raylet is not None:
            e = raylet.object_store.get(object_id)
            if e is not None:
                try:
                    return entry_value(e), True
                except ObjectVanishedError:
                    # Concurrent free won the race: a miss, not a crash
                    # — heal the poisoned entry (else `contains` keeps
                    # short-circuiting pulls "local" forever) and let
                    # the outer loop re-resolve from a real location.
                    self._heal_vanished(object_id)
                    return None, False
        return None, False

    def _heal_vanished(self, object_id: ObjectID, raylet=None) -> None:
        """Drop a local entry whose native backing vanished, and its
        stale directory row for this node, so pulls re-fetch from a
        genuine copy."""
        raylet = raylet or self.local_raylet
        if raylet is None:
            return
        try:
            if raylet.object_store.drop_vanished(object_id):
                self.cluster.object_directory.remove_location(
                    object_id, raylet.node_id)
        except Exception as e:
            # A failed heal leaves the livelock in place — it must be
            # visible, not silent (graftcheck R7 discipline).
            from ray_tpu._private.debug import swallow
            swallow.noted("core_worker.heal_vanished", e)

    def _entry_to_value(self, object_id: ObjectID, entry):
        if entry.error is not None:
            err = entry.error
            if isinstance(err, exceptions.TaskError):
                raise err.as_instanceof_cause()
            raise err
        if isinstance(entry.data, InPlasmaMarker):
            # Marker: the real bytes are in a node store.
            raylet = self.local_raylet
            e = raylet.object_store.get(object_id)
            if e is not None:
                try:
                    return entry_value(e)
                except ObjectVanishedError:
                    self._heal_vanished(object_id)
                    raise _Retry()
            raise _Retry()
        return entry_value(entry)

    def _is_pending(self, object_id: ObjectID) -> bool:
        return self.task_manager.is_pending(object_id.task_id())

    def get_for_executor(self, object_id: ObjectID, node) -> Any:
        """Executor-side arg materialization (GetAndPinArgsForExecutor).

        Loops store-check -> pull -> store-check: a pull can complete via
        the *owner memory store* fast path (small returns are inlined
        there, never copied into the node store), and the producing task
        may seal the entry between any two checks — so after every pull
        both stores are re-read rather than assuming the bytes landed in
        the node store.
        """
        deadline = time.monotonic() + 30.0
        misses = 0
        while True:
            entry = node.object_store.get(object_id)
            if entry is not None:
                try:
                    return entry_value(entry)
                except ObjectVanishedError:
                    # Concurrent free: heal the poisoned entry (and its
                    # stale directory row) so the pull below re-fetches
                    # instead of spinning on a store that claims the
                    # object is local.
                    self._heal_vanished(object_id, raylet=node)
                    entry = None
            entry = self.memory_store.get_entry(object_id)
            if entry is not None and entry.sealed and \
                    not isinstance(entry.data, InPlasmaMarker):
                return self._entry_to_value(object_id, entry)
            if misses:
                # Only reached when a completed "successful" pull did NOT
                # materialize the bytes (e.g. a sealed InPlasmaMarker whose
                # backing node died): back off, and after repeated misses
                # try lineage reconstruction instead of spinning.
                if misses >= 5:
                    self.recover_object(object_id)
                time.sleep(min(0.005 * misses, 0.1))
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise self._lost_error(object_id, "arg fetch failed")
            done = threading.Event()
            ok_box = [False]

            def _cb(ok, done=done, ok_box=ok_box):
                ok_box[0] = ok
                done.set()
            node.object_manager.pull_async(object_id, _cb)
            if not done.wait(timeout=remaining):
                raise self._lost_error(object_id, "arg fetch timed out")
            if not ok_box[0]:
                # Failed pull (e.g. source node died): try lineage
                # reconstruction, then loop to re-check/pull again.
                if not self.recover_object(object_id):
                    raise self._lost_error(
                        object_id, "arg fetch failed and not recoverable")
                time.sleep(0.01)
            else:
                misses += 1  # re-check stores first; sleep only on miss

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List, List]:
        """Event-driven wait: readiness signals are the owner memory
        store sealing (small returns, errors, plasma markers) and the
        directory gaining a location (big returns on any node) — each
        unready ref registers one wakeup hook per source, and the loop
        sleeps on an Event instead of polling (reference: memory-store
        GetAsync + object directory subscription under ``Wait``)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        refs = list(refs)
        wake = threading.Event()
        hooked: Dict[ObjectID, Tuple] = {}

        def hook(object_id: ObjectID):
            if object_id in hooked:
                return
            mem_cb = lambda _e: wake.set()      # noqa: E731
            dir_cb = lambda _n: wake.set()      # noqa: E731
            hooked[object_id] = (mem_cb, dir_cb)
            self.memory_store.get_async(object_id, mem_cb)
            self.cluster.object_directory.subscribe_location(
                object_id, dir_cb)

        try:
            while True:
                ready, not_ready = [], []
                for ref in refs:
                    if self._is_ready(ref.object_id()):
                        ready.append(ref)
                    else:
                        not_ready.append(ref)
                if len(ready) >= num_returns or \
                        (deadline is not None and
                         time.monotonic() >= deadline):
                    return ready, not_ready
                for ref in not_ready:
                    hook(ref.object_id())
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                # Coarse fallback for readiness sources with no hook
                # (e.g. a store state mutated without a directory
                # event): 200 ms, not a hot poll.
                wake.wait(timeout=0.2 if remaining is None
                          else min(remaining, 0.2))
                wake.clear()
        finally:
            # Deregister every hook this call planted — repeated waits
            # on a slow task must not accrete dead closures.
            for object_id, (mem_cb, dir_cb) in hooked.items():
                self.memory_store.cancel_get_async(object_id, mem_cb)
                self.cluster.object_directory.unsubscribe_location(
                    object_id, dir_cb)

    def _is_ready(self, object_id: ObjectID) -> bool:
        entry = self.memory_store.get_entry(object_id)
        if entry is not None and entry.sealed:
            return True
        if self.cluster.object_directory.get_locations(object_id):
            return True
        raylet = self.local_raylet
        return raylet is not None and raylet.object_store.contains(object_id)

    def get_async(self, ref: ObjectRef, callback):
        def run():
            try:
                callback(self._get_one(ref, None), None)
            except BaseException as e:  # noqa: BLE001
                callback(None, e)
        threading.Thread(target=run, daemon=True).start()

    # ---- task submission (core_worker.cc:1650) -------------------------
    def build_args(self, flat_args):
        """Returns (task_args, dep_ids, holders, borrowed_ids).

        ``holders`` are temporary ObjectRefs for big literal args promoted
        to owned objects (put-in-plasma path, _raylet.pyx:1487).  The caller
        MUST keep them alive until ``submit_task`` has registered the
        submitted-task refs, otherwise the Python GC frees the arg object
        between promotion and submission.

        ``borrowed_ids`` are refs nested inside small inlined args — the
        task borrows them for its lifetime (reference: borrower protocol,
        reference_count.h).  They go on the spec so the TaskManager pins
        them while the task is pending and releases them at completion.
        """
        cfg = get_config()
        out: List[TaskArg] = []
        dep_ids: List[ObjectID] = []
        holders: List[ObjectRef] = []
        borrowed: List[ObjectID] = []
        for a in flat_args:
            if isinstance(a, ObjectRef):
                out.append(TaskArg(is_inline=False, object_id=a.object_id(),
                                   owner_id=a.owner_id()))
                dep_ids.append(a.object_id())
            else:
                s = serialize(a)
                if s.total_bytes > cfg.task_args_inline_bytes_limit:
                    ref = self.put(a)
                    holders.append(ref)
                    out.append(TaskArg(is_inline=False,
                                       object_id=ref.object_id(),
                                       owner_id=self.worker_id))
                    dep_ids.append(ref.object_id())
                else:
                    borrowed.extend(r.object_id() for r in s.contained_refs)
                    out.append(TaskArg(is_inline=True, value=s))
        return out, dep_ids, holders, borrowed

    def _provenance(self, spec: TaskSpec) -> Dict[str, Any]:
        """Submit-side provenance for the task event: the parent task id
        and the non-inline arg object ids (the DAG edges `ray-tpu
        profile` reconstructs).  Empty when the profiler is off — the
        event payload stays byte-identical to the pre-profiler wire."""
        if not get_config().job_profiler_enabled:
            return {}
        out: Dict[str, Any] = {}
        if spec.parent_task_id is not None:
            out["parent_task_id"] = spec.parent_task_id.hex()
        args = spec.arg_object_ids()
        if args:
            out["arg_object_ids"] = [oid.hex() for oid in args]
        return out

    def submit_task(self, spec: TaskSpec, holders=()) -> List[ObjectRef]:
        from ray_tpu.gcs import task_events
        from ray_tpu.util import tracing
        self.task_manager.add_pending_task(spec)
        del holders  # submitted-task refs now pin the promoted args
        self.metrics["tasks_submitted"] += 1
        task_events.emit(self.cluster, spec.task_id,
                         task_events.PENDING_ARGS_AVAIL,
                         name=spec.function_name,
                         job_id=spec.job_id.hex(),
                         task_type=spec.task_type,
                         **self._provenance(spec))
        # A spec arriving WITH a trace context (a ray-client submission
        # whose driver-side span already stamped it) continues that
        # trace: its ctx is the parent, and ``force`` records the hop
        # even when this process never enabled capture itself.
        with tracing.span(f"submit:{spec.function_name}",
                          category="submit", parent=spec.trace_ctx,
                          force=bool(spec.trace_ctx),
                          task_id=spec.task_id.hex()) as sp:
            spec.trace_ctx = sp.context() or spec.trace_ctx
            self.task_submitter.submit(spec)
        return [ObjectRef(oid, owner_id=self.worker_id)
                for oid in spec.return_ids]

    def submit_actor_task(self, spec: TaskSpec, holders=()) -> List[ObjectRef]:
        from ray_tpu.gcs import task_events
        from ray_tpu.util import tracing
        self.task_manager.add_pending_task(spec)
        del holders
        self.metrics["actor_tasks_submitted"] += 1
        task_events.emit(self.cluster, spec.task_id,
                         task_events.PENDING_ARGS_AVAIL,
                         name=spec.function_name,
                         job_id=spec.job_id.hex(),
                         task_type=spec.task_type,
                         **self._provenance(spec))
        with tracing.span(f"submit:{spec.function_name}",
                          category="submit", parent=spec.trace_ctx,
                          force=bool(spec.trace_ctx),
                          task_id=spec.task_id.hex()) as sp:
            spec.trace_ctx = sp.context() or spec.trace_ctx
            self.actor_submitter.submit(spec)
        return [ObjectRef(oid, owner_id=self.worker_id)
                for oid in spec.return_ids]

    def create_actor(self, creation_spec: TaskSpec, name: str = "",
                     namespace: str = "", detached: bool = False):
        from ray_tpu.gcs import pubsub as pubsub_mod
        from ray_tpu.gcs.actor_manager import GcsActor

        # Creation args (ref args AND refs inside inlined args) must
        # outlive the ACTOR, not just the creation task — the pinned
        # creation spec re-runs on every restart (reference: actor
        # creation args owned until actor death).  Released on DEAD.
        pinned = creation_spec.arg_object_ids() +             list(creation_spec.borrowed_ids)
        if pinned:
            self.reference_counter.add_submitted_task_refs(pinned)
            released = threading.Event()

            def on_update(_key, info, ids=tuple(pinned)):
                if info.get("state") == "DEAD" and not released.is_set():
                    released.set()
                    self.reference_counter.remove_submitted_task_refs(
                        list(ids))

            self.cluster.gcs.publisher.subscribe(
                pubsub_mod.ACTOR_CHANNEL, creation_spec.actor_id.binary(),
                on_update)
        actor = GcsActor(creation_spec.actor_id, creation_spec, name=name,
                         namespace=namespace,
                         max_restarts=creation_spec.max_restarts,
                         detached=detached)
        self.cluster.gcs.actor_manager.register_actor(actor)
        return actor

    # ---- recovery (object_recovery_manager.cc) -------------------------
    def recover_object(self, object_id: ObjectID, _depth: int = 0) -> bool:
        """Resubmit the creating task from pinned lineage.

        Recovery walks the lineage DAG: lost ARGS of the creating task
        are recovered first (recursively, bounded by
        ``max_lineage_reconstruction_depth`` — a cycle or a chain of
        losses deeper than the bound fails the recovery rather than
        recursing forever).  Repeated reconstructions of the same
        creating task are gated by exponential backoff: within the
        window the call reports in-progress WITHOUT resubmitting, so
        polling get/pull loops cannot stampede the scheduler with
        duplicate resubmissions.  Returns True when the object is being
        recomputed (now or already), False when it is unrecoverable."""
        cfg = get_config()
        if not cfg.lineage_pinning_enabled:
            return False
        if _depth > cfg.max_lineage_reconstruction_depth:
            return False
        spec = self.task_manager.lineage_spec_for_object(object_id)
        if spec is None:
            return False
        if self.task_manager.is_pending(spec.task_id):
            return True  # already being recomputed
        if spec.is_actor_task() or spec.is_actor_creation():
            return False  # actor state is not reconstructable
        now = time.monotonic()
        with self._recon_lock:
            st = self._reconstructions.get(spec.task_id)
            if st is None:
                st = self._reconstructions[spec.task_id] = _ReconState()
            if now < st.next_allowed:
                return True   # backoff window: resubmission pending
            st.attempts += 1
            st.next_allowed = now + cfg.lineage_reconstruction_backoff_s \
                * (2 ** (st.attempts - 1))
            attempt = st.attempts
        # Recover lost args BEFORE resubmitting: the recomputed task
        # cannot run if its own inputs are gone too.
        for arg_id in spec.arg_object_ids():
            if not self._object_available(arg_id):
                self.recover_object(arg_id, _depth=_depth + 1)
        from ray_tpu.gcs import task_events
        from ray_tpu._private.debug import flight_recorder
        flight_recorder.record(
            "lineage.reconstruct", obj=object_id.hex()[:12],
            task=spec.task_id.hex()[:12], attempt=attempt, depth=_depth)
        self.metrics["lineage_reconstructions"] += 1
        # Attempt rides above the retry band (prior retries never
        # exceed max_retries) so the task-event manager rewinds the
        # FINISHED record into RECONSTRUCTING, retry-style.
        task_events.emit(self.cluster, spec.task_id,
                         task_events.RECONSTRUCTING,
                         name=spec.function_name,
                         attempt=spec.max_retries + attempt)
        self.task_manager.add_pending_task(spec)
        self.task_submitter.submit(spec)
        return True

    def _lost_error(self, object_id: ObjectID,
                    reason: str) -> exceptions.ObjectLostError:
        """Build an ObjectLostError with actionable context: who owned
        the object, where its copies last were, whether lineage could
        (or did) try to recompute it, and any spill record — the
        debugging trail for "why is my object gone"."""
        parts = [reason]
        ref = self.reference_counter.describe(object_id)
        if ref is not None:
            parts.append("owner worker=" +
                         ("this driver" if ref["owned"] else "borrowed") +
                         f" ({self.worker_id.hex()[:12]})")
            if ref.get("spilled_url"):
                parts.append(f"spilled_url={ref['spilled_url']}")
        locations = self.cluster.object_directory.get_locations(object_id)
        parts.append("known locations=" +
                     (",".join(n.hex()[:12] for n in locations)
                      if locations else "none"))
        spec = self.task_manager.lineage_spec_for_object(object_id)
        if spec is None:
            parts.append("lineage=not pinned (cannot reconstruct; "
                         "check lineage_pinning_enabled / "
                         "max_lineage_bytes)")
        elif spec.is_actor_task() or spec.is_actor_creation():
            parts.append(f"lineage={spec.function_name} is an actor "
                         "task (actor state is not reconstructable)")
        else:
            with self._recon_lock:
                st = self._reconstructions.get(spec.task_id)
                attempts = st.attempts if st is not None else 0
            parts.append(f"lineage=pinned ({spec.function_name}), "
                         f"{attempts} reconstruction attempt(s)")
        return exceptions.ObjectLostError(object_id, "; ".join(parts))

    def _object_available(self, object_id: ObjectID) -> bool:
        """An object needs no recovery: sealed value (not a marker
        pointing at a store that may have died), a live store location,
        or a pending producing task."""
        entry = self.memory_store.get_entry(object_id)
        if entry is not None and entry.sealed and \
                not isinstance(entry.data, InPlasmaMarker):
            return True
        if self.cluster.object_directory.get_locations(object_id):
            return True
        return self.task_manager.is_pending(object_id.task_id())

    def on_node_death(self, node_id, lost_objects: List[ObjectID]):
        """Proactively reconstruct referenced lost objects."""
        for oid in lost_objects:
            if self.reference_counter.has_reference(oid):
                self.memory_store.delete(oid)
                self.recover_object(oid)

    def fail_owned_object(self, object_id: ObjectID,
                          error: BaseException):
        """Owner-death invalidation: seal ``error`` over the object so
        every borrower's get/wait raises instead of hanging, and drop
        the now-ownerless copies (reference: reference_count.cc OWNER
        _DIED propagation / WaitForRefRemoved teardown)."""
        self.memory_store.fail(object_id, error)
        directory = self.cluster.object_directory
        for node_id in directory.get_locations(object_id):
            raylet = self.cluster.gcs.raylet(node_id)
            if raylet is not None:
                try:
                    raylet.object_store.delete(object_id)
                except Exception:
                    pass
        directory.remove_object(object_id)

    # ---- free path ------------------------------------------------------
    def _free_object(self, object_id: ObjectID):
        self.memory_store.delete(object_id)
        directory = self.cluster.object_directory
        for node_id in directory.get_locations(object_id):
            raylet = self.cluster.gcs.raylet(node_id)
            if raylet is not None:
                raylet.object_store.delete(object_id)
        directory.remove_object(object_id)
        self.task_manager.evict_lineage(object_id.task_id())
        with self._recon_lock:
            self._reconstructions.pop(object_id.task_id(), None)

    def free_objects(self, refs: Sequence[ObjectRef]):
        for ref in refs:
            self._free_object(ref.object_id())

    # ---- metrics hook ---------------------------------------------------
    def record_task_metric(self, spec: TaskSpec, elapsed: float):
        self.metrics["tasks_finished"] += 1
        self.metrics["task_exec_seconds"] += elapsed


def _is_device_array(value) -> bool:
    """True for live jax device arrays — without importing jax eagerly
    (jax import costs seconds; pure-CPU control paths never pay it)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return isinstance(value, jax.Array) and not value.is_deleted()
    except AttributeError:
        # jax is mid-import on ANOTHER thread (the module is in
        # sys.modules before its attributes exist) — same race the
        # serialization path guards.  A partially-imported jax has no
        # live device arrays to mishandle.
        return False


class _ReconState:
    """Reconstruction bookkeeping for one creating task."""

    __slots__ = ("attempts", "next_allowed")

    def __init__(self):
        self.attempts = 0
        self.next_allowed = 0.0


class _Retry(Exception):
    pass
