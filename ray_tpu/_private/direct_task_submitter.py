"""Normal-task transport: leasing, pipelining, spillback handling.

Parity: reference ``src/ray/core_worker/transport/direct_task_transport.cc``
— per-``SchedulingKey`` queues (direct_task_transport.h:53-57), worker lease
reuse (``OnWorkerIdle`` .cc:157), new lease requests capped per scheduling
class (``RequestNewWorkerIfNeeded`` .cc:308), spillback re-lease at
``retry_at_raylet_address`` (.cc:459), direct ``PushTask`` to the leased
worker (.cc:508) — the raylet is off the per-task data path after leasing.

Lease-node choice uses the locality policy (``lease_policy.h:54-60``): the
raylet holding the most argument bytes, else the local raylet.

Dispatch fast path (three levers on the submit->running hot path):

* **Batched leases** — ``_pump`` coalesces a same-class burst into ONE
  ``request_worker_lease_batch`` round-trip for up to ``lease_batch_size``
  workers; the reply's grant/spillback vector is handled entry-wise
  (spillbacks re-lease individually, exactly like the single path), and
  ``backlog`` entries stay client-side until a progress edge re-pumps.
* **Lease keepalive** — an idle leased worker is parked for
  ``worker_lease_keepalive_ms`` instead of returned, so the next
  same-class task is pushed directly with zero scheduling round-trips
  (lease pipelining across get()-separated bursts).
* Tasks pushed onto a reused/parked lease never traverse the raylet
  scheduler, so the transport emits their SCHEDULED transition itself at
  push time — the queue_wait stage covers every task, not just the
  slow path (the BENCH_r06 118-of-700 coverage gap).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

from ray_tpu import exceptions
from ray_tpu._private.config import get_config
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu._private.debug import diag_rlock, swallow

# Re-lease cadence/window for leases bounced off a not-yet-declared-dead
# node: 0.2s x 150 = 30s, comfortably past any heartbeat-timeout
# declaration, after which the bounce becomes a real failure.
_LEASE_BOUNCE_DELAY_S = 0.2
_MAX_LEASE_BOUNCES = 150


def _worker_dead(worker) -> bool:
    return str(getattr(worker, "state", "")) == "DEAD"


class _SchedulingKeyState:
    __slots__ = ("queue", "idle_workers", "pending_leases",
                 "leased_task_ids", "backlog_retry_pending", "backoff",
                 "request_in_flight")

    def __init__(self):
        self.queue: deque = deque()
        # One NEW lease request (single or batch) outstanding per class
        # at a time: issuing one per queued task (the old pipelining)
        # leased a worker per SCHEDULED task of the burst — dozens of
        # workers started, granted and returned unused at drain end —
        # while the batch reply tells us within one round-trip how many
        # workers the cluster can actually give us.  Spillback/bounce
        # re-leases of already-accounted entries bypass the gate.
        self.request_in_flight = False
        # True after a backlog reply (raylet: feasible, no capacity):
        # stop issuing new lease requests for this class until a real
        # capacity edge — a grant, a lease return, the backlog-retry
        # probe — clears it.  Without this, every submit during a
        # saturated burst would re-issue a futile batch round-trip.
        self.backoff = False
        # Parked (worker, raylet) leases kept warm for direct push —
        # each parking arms a keepalive timer that returns the lease if
        # no task claims it inside the window.
        self.idle_workers: List[Tuple[object, object]] = []
        self.pending_leases = 0
        # Task ids with an in-flight lease request: each lease request must
        # carry a DISTINCT representative spec — the raylet dep-waits on the
        # representative's args, and two in-flight waits for one task id
        # would collide (reference: pending_lease_requests_ keyed by TaskID,
        # direct_task_transport.h).
        self.leased_task_ids: set = set()
        # One delayed re-pump armed per class while a pure-backlog batch
        # reply left the queue without any other progress edge.
        self.backlog_retry_pending = False


class DirectTaskSubmitter:
    def __init__(self, core_worker):
        self._core = core_worker
        self._lock = diag_rlock("DirectTaskSubmitter._lock")
        self._keys: Dict[int, _SchedulingKeyState] = defaultdict(
            _SchedulingKeyState)
        self._lease_bounces: Dict = {}   # task_id -> transient rejects
        self._max_pending = get_config(
        ).max_pending_lease_requests_per_scheduling_category

    # ---- entry ----------------------------------------------------------
    def submit(self, spec: TaskSpec):
        key = spec.scheduling_class
        with self._lock:
            state = self._keys[key]
            state.queue.append(spec)
            depth = len(state.queue)
        self._pump(key)
        bp = get_config().submit_backpressure_depth
        if bp and depth > bp:
            # Flow control: the submitting thread is outrunning the
            # pipeline — yield the GIL so workers drain the backlog it
            # just grew (queue_wait latency is bounded by ~depth x
            # per-task cost instead of the whole burst).
            time.sleep(0)

    def _pump(self, key: int):
        """Dispatch queued tasks onto idle leased workers; coalesce the
        unleased remainder into one batched lease request (bounded
        pipelining: in-flight lease entries are capped per class)."""
        cfg = get_config()
        while True:
            dead_entry = None
            push_pair = None
            batch: List[TaskSpec] = []
            with self._lock:
                state = self._keys[key]
                if not state.queue:
                    return
                if state.idle_workers:
                    worker, raylet = state.idle_workers.pop()[:2]
                    if _worker_dead(worker):
                        dead_entry = (worker, raylet)
                    else:
                        # Pop under the lock, push OUTSIDE it: the push
                        # (task events + worker queue) is the per-task
                        # hot path, and holding the class-wide lock
                        # through it serializes every worker's reuse
                        # cycle against every other's.
                        push_pair = (state.queue.popleft(), worker,
                                     raylet)
                else:
                    if state.backoff or state.request_in_flight:
                        return   # no capacity / a request already out
                    avail = self._max_pending - state.pending_leases
                    if avail <= 0:
                        return
                    cap = min(avail, max(1, cfg.lease_batch_size))
                    # Specs with ref args never join a batch: the
                    # raylet dep-waits on the representative's args,
                    # and the batch reply fires only when EVERY entry
                    # resolves — a consumer waiting on outputs of
                    # same-batch producers would withhold the
                    # producers' granted workers behind itself
                    # (deadlock when no prior lease exists to drain
                    # them by reuse).  They ride the single-lease path,
                    # whose reply is held per entry exactly as before.
                    fallback = None
                    for s in state.queue:
                        if s.task_id in state.leased_task_ids:
                            continue
                        if s.arg_object_ids():
                            if fallback is None:
                                fallback = s
                            continue
                        batch.append(s)
                        if len(batch) >= cap:
                            break
                    if not batch and fallback is not None:
                        batch = [fallback]
                    if not batch:
                        return  # every queued task has a lease in flight
                    state.request_in_flight = True
                    state.pending_leases += len(batch)
                    state.leased_task_ids.update(
                        s.task_id for s in batch)
            if dead_entry is not None:
                # Died while parked: the lease is useless, give it back
                # (outside our lock — return_worker walks raylet-side
                # locks) and keep pumping.
                try:
                    dead_entry[1].return_worker(dead_entry[0],
                                                disconnect=True)
                except Exception as e:
                    swallow.noted("submitter.dead_parked_return", e)
                continue
            if push_pair is not None:
                self._push(push_pair[0], push_pair[1], push_pair[2], key)
                continue
            if len(batch) == 1:
                self._request_lease(batch[0], key, clears_gate=True)
            else:
                self._request_lease_batch(batch, key)
            return

    # ---- leasing --------------------------------------------------------
    def _pick_lease_raylet(self, spec: TaskSpec):
        """Locality-aware lease policy (lease_policy.h:54-60)."""
        best, best_bytes = None, -1
        cluster = self._core.cluster
        for oid in spec.arg_object_ids():
            locs = cluster.object_directory.get_locations(oid)
            for node_id in locs:
                raylet = cluster.gcs.raylet(node_id)
                if raylet is None:
                    continue
                entry = raylet.object_store.get(oid)
                size = entry.size if entry else 0
                if size > best_bytes:
                    best, best_bytes = raylet, size
        if spec.scheduling_options.node_affinity_node_id is not None:
            affinity = cluster.gcs.raylet(
                spec.scheduling_options.node_affinity_node_id)
            if affinity is not None:
                return affinity
        return best or self._core.local_raylet

    def _clear_request_gate(self, key: int):
        with self._lock:
            self._keys[key].request_in_flight = False

    def _request_lease(self, spec: TaskSpec, key: int, raylet=None,
                       hops: int = 0, clears_gate: bool = False):
        """``clears_gate`` marks the class's ONE gated new-lease request
        (issued by ``_pump``); spillback/bounce re-leases of an
        already-accounted entry leave the gate alone."""
        raylet = raylet or self._pick_lease_raylet(spec)
        if raylet is None:
            if clears_gate:
                self._clear_request_gate(key)
            self._on_lease_failed(spec, key,
                                  exceptions.RayTpuError("no raylet"))
            return

        def on_reply(result):
            if clears_gate:
                self._clear_request_gate(key)
            self._on_lease_result(spec, key, result, hops)

        raylet.request_worker_lease(spec, on_reply)

    def _request_lease_batch(self, specs: List[TaskSpec], key: int):
        """One round-trip for up to ``lease_batch_size`` same-class
        workers.  The batch targets the first spec's locality choice —
        same scheduling class means same resources/options, and the
        raylet's own policy corrects any per-task locality difference
        via spillback (re-leased individually as today)."""
        raylet = self._pick_lease_raylet(specs[0])
        if raylet is None:
            self._clear_request_gate(key)
            for s in specs:
                self._on_lease_failed(s, key,
                                      exceptions.RayTpuError("no raylet"))
            return
        batch_fn = getattr(raylet, "request_worker_lease_batch", None)
        if batch_fn is None:
            # Transport without the batched RPC: plain single leases.
            self._clear_request_gate(key)
            for s in specs:
                self._request_lease(s, key, raylet=raylet)
            return

        def on_reply(reply):
            # Re-open the gate first: a grant below may pump the next
            # batch while the rest of this reply is still processing.
            self._clear_request_gate(key)
            results = (reply or {}).get("results") or []
            progress = False
            for i, spec in enumerate(specs):
                result = results[i] if i < len(results) else {
                    "rejected": True, "reason": "batch reply truncated"}
                if "worker" in result or "retry_at" in result:
                    progress = True
                self._on_lease_result(spec, key, result, 0)
            if not progress:
                # Pure backlog/bounce: nothing above re-pumps, and the
                # raylet no longer holds our entries — arm the delayed
                # re-pump fallback so the class can't starve.
                self._schedule_backlog_retry(key)

        batch_fn(specs, on_reply)

    def _on_lease_result(self, spec: TaskSpec, key: int, result: dict,
                         hops: int):
        """Shared per-entry lease resolution (single and batched)."""
        if "worker" in result:
            self._handle_grant(spec, key, result)
        elif "retry_at" in result:
            # Spillback (cluster_task_manager.cc:285-323): re-lease at
            # the suggested raylet.
            target = self._core.cluster.gcs.raylet(result["retry_at"])
            if target is None or hops > 10:
                with self._lock:
                    self._keys[key].pending_leases -= 1
                    self._keys[key].leased_task_ids.discard(spec.task_id)
                self._pump(key)
            else:
                self._request_lease(spec, key, raylet=target,
                                    hops=hops + 1)
        elif result.get("backlog"):
            if result.get("infeasible"):
                # No node's totals fit: re-lease through the SINGLE
                # path, which parks raylet-side until the cluster
                # changes (autoscaler demand stays visible there).
                # Accounting unchanged — the entry is still in flight.
                self._request_lease(spec, key)
            else:
                # Feasible but no capacity this tick: the task stays in
                # our queue under lease back-off; a capacity edge (a
                # grant, a lease return) or the backlog-retry probe
                # re-opens leasing, and parked-lease reuse keeps
                # draining the queue meanwhile.
                with self._lock:
                    state = self._keys[key]
                    state.pending_leases = max(0, state.pending_leases - 1)
                    state.leased_task_ids.discard(spec.task_id)
                self._schedule_backlog_retry(key)
        elif result.get("batch_fault"):
            # The whole batch bounced (chaos point worker.lease_batch /
            # a transport refusing the batched RPC): retry this entry
            # on the single-lease path — a scheduling-plane hiccup,
            # never a task failure, so no retry budget is charged.
            self._request_lease(spec, key)
        else:
            reason = str(result.get("reason", "lease rejected"))
            transient = bool(result.get("rejected")) and (
                "connection lost" in reason or "node dead" in reason)
            self._on_lease_failed(
                spec, key, exceptions.RayTpuError(reason),
                transient=transient)

    def _handle_grant(self, spec: TaskSpec, key: int, result: dict):
        worker, raylet = result["worker"], result["raylet"]
        if _worker_dead(worker):
            # The worker died between grant and push (batched grants
            # widen this window): give the lease back — that frees the
            # raylet-side resource reservation — and re-lease via the
            # next pump WITHOUT burning the task's retry budget; the
            # task never reached a worker.
            try:
                raylet.return_worker(worker, disconnect=True)
            except Exception as e:
                swallow.noted("submitter.dead_grant_return", e)
            with self._lock:
                state = self._keys[key]
                state.pending_leases = max(0, state.pending_leases - 1)
                state.leased_task_ids.discard(spec.task_id)
            self._pump(key)
            return
        with self._lock:
            state = self._keys[key]
            state.pending_leases -= 1
            state.leased_task_ids.discard(spec.task_id)
            state.backoff = False      # capacity edge: leasing works again
            self._lease_bounces.pop(spec.task_id, None)
            if state.queue and state.queue[0].task_id == spec.task_id:
                state.queue.popleft()
                dispatch = spec
            elif state.queue:
                dispatch = state.queue.popleft()
            else:
                dispatch = None
            if dispatch is not None:
                state.leased_task_ids.discard(dispatch.task_id)
        if dispatch is None:
            # Queue drained while the lease was in flight; return it.
            raylet.return_worker(worker)
        else:
            self._push(dispatch, worker, raylet, key)
        self._pump(key)

    def _schedule_backlog_retry(self, key: int):
        """Back the class off and arm its delayed re-pump — the raylet
        dropped our backlog entries, so no held reply will wake us when
        capacity frees.  Backoff and timer are set under ONE lock hold:
        a backoff left set without a pending timer (e.g. because the
        queue looked empty for an instant between bursts) would gate
        every future submit of the class forever.  Rides the raylet
        loop's timer heap (one pending timer per class, not one thread
        per bounce)."""
        raylet = self._core.local_raylet
        if raylet is None or getattr(raylet, "_dead", False):
            with self._lock:
                self._keys[key].backoff = False
            return
        with self._lock:
            state = self._keys[key]
            if not state.queue:
                # Nothing left to lease for: do not gate future
                # submits.
                state.backoff = False
                return
            state.backoff = True
            if state.backlog_retry_pending:
                return
            state.backlog_retry_pending = True

        def fire():
            with self._lock:
                state = self._keys[key]
                state.backlog_retry_pending = False
                state.backoff = False      # probe: try leasing again
            local = self._core.local_raylet
            if local is None or getattr(local, "_dead", False):
                return
            self._pump(key)

        delay = max(1, get_config().lease_backlog_retry_ms) / 1000.0
        raylet.loop.schedule_after(delay, fire, "lease.backlog_retry")

    def _on_lease_failed(self, spec: TaskSpec, key: int, err,
                         transient: bool = False):
        with self._lock:
            state = self._keys[key]
            state.pending_leases = max(0, state.pending_leases - 1)
            state.leased_task_ids.discard(spec.task_id)
            try:
                state.queue.remove(spec)
            except ValueError:
                pass
        if transient:
            # The lease bounced off a dying/unreachable node whose death
            # the GCS has not declared yet, so the scheduler may keep
            # pointing at it for a few heartbeats.  That is a
            # scheduling-plane hiccup, not a task failure: hold the spec
            # and re-lease after a beat WITHOUT burning the task's retry
            # budget (reference: lease failures against a dead raylet are
            # retried at the lease layer, task retries cover execution).
            # Bounded — past the window it becomes a real failure.
            with self._lock:
                n = self._lease_bounces.get(spec.task_id, 0) + 1
                self._lease_bounces[spec.task_id] = n
            if n <= _MAX_LEASE_BOUNCES:
                # Delayed re-lease rides the raylet event loop's timer
                # heap — a node death can bounce hundreds of queued
                # tasks every 0.2s for several heartbeats, and a Timer
                # THREAD per bounce would be thread churn exactly while
                # the scheduler is busiest.
                raylet = self._core.local_raylet
                if raylet is not None and not getattr(raylet, "_dead",
                                                      False):
                    raylet.loop.schedule_after(
                        _LEASE_BOUNCE_DELAY_S,
                        lambda: self._resubmit_bounced(spec),
                        "lease.rebounce")
                return
        with self._lock:
            self._lease_bounces.pop(spec.task_id, None)
        self._core.task_manager.fail_or_retry(
            spec, err, resubmit=self.submit)

    def _resubmit_bounced(self, spec: TaskSpec):
        """Timer-thread re-lease of a transiently bounced task.  A
        cluster torn down while the timer was pending must not be
        resubmitted into (the re-lease would bounce-loop against dead
        raylets across later tests in the same process)."""
        raylet = self._core.local_raylet
        if raylet is None or getattr(raylet, "_dead", False):
            return
        self.submit(spec)

    # ---- dispatch -------------------------------------------------------
    def _push(self, spec: TaskSpec, worker, raylet, key: int):
        from ray_tpu.gcs import task_events
        nid = getattr(worker, "node_id", None)
        wid = getattr(worker, "worker_id", None)
        nid_hex = nid.hex() if nid is not None else ""
        # Transport-side SCHEDULED: the binding of THIS task to a worker
        # is decided here, and tasks riding a reused/parked lease never
        # traverse the raylet scheduler at all — without this emit their
        # queue_wait stage has no sample and the histogram only covers
        # the slow path.  For scheduler-path tasks whose raylet-side
        # SCHEDULED shares this buffer (the in-process head raylet) the
        # manager's first-arrival dedup keeps the raylet's earlier
        # timestamp; a REMOTE raylet's SCHEDULED rides its own buffer
        # and can arrive after this one, in which case queue_wait
        # absorbs the scheduled->push interval and dispatch reads ~0 —
        # the same conservative direction as the decomposition's
        # documented SUBMITTED-before-SCHEDULED approximation (total is
        # unaffected either way).
        task_events.emit(self._core.cluster, spec.task_id,
                         task_events.SCHEDULED, node_id=nid_hex)
        task_events.emit(self._core.cluster, spec.task_id,
                         task_events.SUBMITTED_TO_WORKER,
                         node_id=nid_hex,
                         worker_id=wid.hex() if wid is not None else "")

        def on_done(error):
            if error is None:
                self._core.task_manager.complete_task(spec)
                self._on_worker_idle(worker, raylet, key)
            else:
                # User errors don't poison the worker; system errors do.
                if isinstance(error, exceptions.TaskError):
                    self._on_worker_idle(worker, raylet, key)
                else:
                    raylet.return_worker(worker, disconnect=True)
                retried = self._core.task_manager.fail_or_retry(
                    spec, error, resubmit=self.submit)
                _ = retried

        worker.push_task(spec, on_done)
        # Cross-thread push: the target worker needs the GIL to START
        # the task, and the pushing thread (driver submit loop, raylet
        # loop, another worker's idle path) would otherwise keep
        # running a full switch interval — measured as the dominant
        # ``startup``-stage tail.  One yield hands the task over now.
        # A push from the worker's own thread (the reuse cycle) never
        # yields: the worker's loop picks the task up immediately.
        thr = getattr(worker, "_thread", None)
        if thr is not threading.current_thread():
            time.sleep(0)

    def _on_worker_idle(self, worker, raylet, key: int):
        """Reuse the leased worker for the next queued task of this class
        (OnWorkerIdle, direct_task_transport.cc:157); with no backlog,
        park the lease warm for ``worker_lease_keepalive_ms`` so a
        burst arriving inside the window pushes directly instead of
        paying a fresh lease round-trip."""
        spec = None
        with self._lock:
            state = self._keys[key]
            if state.queue:
                spec = state.queue.popleft()
        if spec is not None:
            # Push outside the lock (see _pump): this is the per-task
            # reuse hot path every worker cycles through concurrently.
            self._push(spec, worker, raylet, key)
            return
        keepalive = get_config().worker_lease_keepalive_ms / 1000.0
        local = self._core.local_raylet
        if keepalive <= 0 or _worker_dead(worker) or local is None \
                or getattr(local, "_dead", False):
            # No more work: return the lease.
            raylet.return_worker(worker)
            return
        # Per-park identity sentinel: entries must NOT compare equal
        # across parks of the same worker, or a stale keepalive timer
        # from an earlier park would `remove` (and return) a freshly
        # re-parked lease — capping the effective keepalive at
        # first-park + window under steady reuse.
        entry = (worker, raylet, object())
        with self._lock:
            state = self._keys[key]
            if state.queue:
                # A submit raced the park: its _pump saw neither a
                # parked worker nor a reason to lease, so if we parked
                # now the task would wait with nothing ever waking it
                # (lost-wakeup deadlock).  Pop-or-park must be atomic.
                spec = state.queue.popleft()
            else:
                state.idle_workers.append(entry)
        if spec is not None:
            self._push(spec, worker, raylet, key)
            return
        local.loop.schedule_after(
            keepalive, lambda: self._expire_idle(key, entry),
            "lease.keepalive")

    def _expire_idle(self, key: int, entry):
        """Keepalive lapsed: if the parked lease is still unclaimed,
        return it (and its resource reservation) to the raylet."""
        with self._lock:
            state = self._keys[key]
            try:
                state.idle_workers.remove(entry)
            except ValueError:
                return   # claimed by a push in the window
        worker, raylet = entry[0], entry[1]
        try:
            raylet.return_worker(worker)
        except Exception as e:
            swallow.noted("submitter.keepalive_return", e)
        # The returned lease freed raylet-side capacity: give the class
        # a progress edge in case work arrived while we held it parked.
        with self._lock:
            has_work = bool(self._keys[key].queue)
        if has_work:
            self._pump(key)
